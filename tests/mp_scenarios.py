"""Multi-process test scenarios, run as subprocesses by
test_multiprocess.py — the TPU build's analog of the reference running
its pytest suite under ``mpirun -np 2`` (reference: .travis.yml:109-122).

Each scenario function runs on every rank with hvd initialized; it must
assert its own correctness and return. Invoked as:

    python -m tests.mp_scenarios <scenario> <rank> <size> <port>
"""

import os
import sys

import numpy as np


def scenario_allreduce(hvd, rank, size):
    x = np.full((4, 3), float(rank + 1), np.float32)
    out = hvd.allreduce(x, average=False, name="ar")
    expected = np.full((4, 3), sum(range(1, size + 1)), np.float32)
    np.testing.assert_allclose(out, expected)
    # average
    out = hvd.allreduce(x, average=True, name="ar_avg")
    np.testing.assert_allclose(
        out, expected / size)


def scenario_allreduce_fused(hvd, rank, size):
    """Many small async allreduces in one cycle → fused execution
    (reference analog: test_horovod_allreduce_cpu_fused,
    test_tensorflow.py:107)."""
    handles = [hvd.allreduce_async(
        np.full(10, float(rank + 1) * (i + 1), np.float64),
        average=False, name=f"f/{i}") for i in range(30)]
    ssum = sum(range(1, size + 1))
    for i, h in enumerate(handles):
        np.testing.assert_allclose(
            hvd.synchronize(h), np.full(10, ssum * (i + 1), np.float64))


def scenario_allreduce_multi_dtype(hvd, rank, size):
    for dt in (np.int32, np.int64, np.float16, np.float32, np.float64):
        x = (np.arange(6) + rank).astype(dt)
        out = hvd.allreduce(x, average=False, name=f"dt/{np.dtype(dt)}")
        expected = (size * np.arange(6) + sum(range(size))).astype(dt)
        np.testing.assert_allclose(np.asarray(out, np.float64),
                                   expected.astype(np.float64))


def scenario_allgather(hvd, rank, size):
    # variable dim-0 per rank (reference: test_tensorflow.py:454-557)
    x = np.full((rank + 1, 2), float(rank), np.float32)
    out = hvd.allgather(x, name="ag")
    assert out.shape == (sum(r + 1 for r in range(size)), 2)
    offset = 0
    for r in range(size):
        np.testing.assert_allclose(out[offset:offset + r + 1],
                                   np.full((r + 1, 2), float(r)))
        offset += r + 1


def scenario_broadcast(hvd, rank, size):
    for root in range(size):
        x = np.full((3, 3), float(rank * 10), np.float64)
        out = hvd.broadcast(x, root_rank=root, name=f"bc/{root}")
        np.testing.assert_allclose(out, np.full((3, 3), float(root * 10)))


def scenario_alltoall(hvd, rank, size):
    per = 2
    x = np.arange(size * per, dtype=np.float32) + 100 * rank
    out = hvd.alltoall(x, name="a2a")
    expected = np.concatenate(
        [np.arange(rank * per, (rank + 1) * per) + 100 * src
         for src in range(size)]).astype(np.float32)
    np.testing.assert_allclose(out, expected)


def scenario_reducescatter(hvd, rank, size):
    x = np.arange(size * 3, dtype=np.float32) * (rank + 1)
    out = hvd.reducescatter(x, name="rs")
    ssum = sum(range(1, size + 1))
    expected = (np.arange(size * 3, dtype=np.float32)
                * ssum)[rank * 3:(rank + 1) * 3]
    np.testing.assert_allclose(out, expected)


def scenario_ring_allreduce(hvd, rank, size):
    """Payloads over the (harness-lowered) threshold ride the ring data
    plane; small ones keep the star; reducescatter reuses the same ring.
    (Reference analog: MPI_Allreduce's internal ring algorithms,
    mpi_operations.cc:25-84.)"""
    from horovod_tpu.common import basics as _b
    ssum = sum(range(1, size + 1))

    n = 100_000
    x = np.arange(n, dtype=np.float64) + rank
    out = hvd.allreduce(x, average=False, name="ring.big")
    np.testing.assert_allclose(
        out, size * np.arange(n, dtype=np.float64) + sum(range(size)))

    rt = _b.runtime()
    sock = [b for b in rt.op_manager._backends if b.name == "socket"][0]
    assert sock._ring is not None, "ring was not established"

    # below threshold -> star path, after the ring already exists
    y = np.full(8, float(rank + 1), np.float32)
    np.testing.assert_allclose(
        hvd.allreduce(y, average=False, name="ring.small"), ssum)

    # non-in-place contract: the caller's array must survive the ring
    z = np.full(50_000, float(rank + 1), np.float32)
    out = hvd.allreduce(z, average=True, name="ring.big2")
    np.testing.assert_allclose(out, ssum / size)
    np.testing.assert_allclose(z, float(rank + 1))

    # fused batch over the threshold -> one ring op for the whole pack
    handles = [hvd.allreduce_async(
        np.full(20_000, float(rank + 1) * (i + 1), np.float64),
        average=False, name=f"ring.f/{i}") for i in range(4)]
    for i, h in enumerate(handles):
        np.testing.assert_allclose(
            hvd.synchronize(h), ssum * (i + 1))

    # reducescatter on the same ring (phase-1-only schedule)
    per = 4096
    rs = np.arange(size * per, dtype=np.float64) * (rank + 1)
    out = hvd.reducescatter(rs, name="ring.rs")
    expected = (np.arange(size * per, dtype=np.float64)
                * ssum)[rank * per:(rank + 1) * per]
    np.testing.assert_allclose(out, expected)


def scenario_ring_fallback(hvd, rank, size):
    """Ring establishment failing on ONE rank must degrade the whole
    world to the star path by agreement (ops/ring.py establish():
    port -1 advertisement + agree()) — no divergence, results correct."""
    from horovod_tpu.common import basics as _b
    from horovod_tpu.common import network as _net

    orig_listen = _net.listen
    if rank == 1:
        def _fail(*a, **k):
            raise OSError("forced listen failure (test)")
        _net.listen = _fail

    x = np.full(100_000, float(rank + 1), np.float64)
    out = hvd.allreduce(x, average=False, name="rf.big")
    np.testing.assert_allclose(out, sum(range(1, size + 1)))

    rt = _b.runtime()
    sock = [b for b in rt.op_manager._backends if b.name == "socket"][0]
    assert sock._ring_tried, "ring establishment was never attempted"
    assert sock._ring is None, "ring must not exist after a failed vote"

    _net.listen = orig_listen
    # the world stays on the star path (establishment is tried once)
    out = hvd.allreduce(x, average=False, name="rf.big2")
    np.testing.assert_allclose(out, sum(range(1, size + 1)))


def scenario_shm_collectives(hvd, rank, size):
    """All five collectives + fused batch + segment growth on the
    shared-memory backend (same-host world selects it automatically)."""
    from horovod_tpu.common import basics as _b
    rt = _b.runtime()
    shm = [b for b in rt.op_manager._backends if b.name == "shm"][0]
    ssum = sum(range(1, size + 1))

    # allreduce (small -> establishes the first segment)
    x = np.full((4, 3), float(rank + 1), np.float32)
    np.testing.assert_allclose(
        hvd.allreduce(x, average=False, name="shm.ar"),
        np.full((4, 3), ssum, np.float32))
    assert shm._map is not None, "shm segment not established"
    gen0 = shm._gen

    # large allreduce -> segment must grow (re-establishment)
    big = np.arange(300_000, dtype=np.float64) + rank
    np.testing.assert_allclose(
        hvd.allreduce(big, average=False, name="shm.big"),
        size * np.arange(300_000, dtype=np.float64) + sum(range(size)))
    assert shm._gen > gen0, "segment did not grow for the larger payload"

    # input must never be mutated (slots are written, results copied out)
    np.testing.assert_allclose(big, np.arange(300_000) + rank)

    # fused batch in one cycle
    handles = [hvd.allreduce_async(
        np.full(1000, float(rank + 1) * (i + 1), np.float64),
        average=False, name=f"shm.f/{i}") for i in range(8)]
    for i, h in enumerate(handles):
        np.testing.assert_allclose(
            hvd.synchronize(h), ssum * (i + 1))

    # variable-dim0 allgather
    g = hvd.allgather(
        np.full((rank + 1, 2), float(rank), np.float32), name="shm.ag")
    assert g.shape == (sum(r + 1 for r in range(size)), 2)
    offset = 0
    for r in range(size):
        np.testing.assert_allclose(g[offset:offset + r + 1], float(r))
        offset += r + 1

    # broadcast from every root (incl. non-coordinator roots)
    for root in range(size):
        out = hvd.broadcast(np.full(5, float(rank * 10), np.float64),
                            root_rank=root, name=f"shm.bc/{root}")
        np.testing.assert_allclose(out, float(root * 10))

    # alltoall
    per = 2
    a = np.arange(size * per, dtype=np.float32) + 100 * rank
    out = hvd.alltoall(a, name="shm.a2a")
    expected = np.concatenate(
        [np.arange(rank * per, (rank + 1) * per) + 100 * src
         for src in range(size)]).astype(np.float32)
    np.testing.assert_allclose(out, expected)

    # reducescatter
    rs = np.arange(size * 3, dtype=np.float32) * (rank + 1)
    out = hvd.reducescatter(rs, name="shm.rs")
    np.testing.assert_allclose(
        out, (np.arange(size * 3, dtype=np.float32)
              * ssum)[rank * 3:(rank + 1) * 3])

    hvd.barrier(name="shm.bar")


def scenario_edge_shapes(hvd, rank, size):
    """Zero-size and 0-d tensors through the collectives: negotiated
    like anything else, correct shapes out, no wedged protocol. Run
    under both the shm and socket planes by the harness."""
    z = hvd.allreduce(np.empty(0, np.float32), average=False,
                      name="e.zero")
    assert np.asarray(z).shape == (0,)

    out = hvd.allreduce(np.asarray(3.0 * (rank + 1), np.float64),
                        average=False, name="e.scalar")
    assert np.asarray(out).shape == ()
    assert float(out) == 3.0 * sum(range(1, size + 1))

    # every rank empty
    g = hvd.allgather(np.empty((0, 4), np.float32), name="e.ag0")
    assert np.asarray(g).shape == (0, 4)

    # SOME ranks empty (rank 0 contributes nothing)
    g = hvd.allgather(np.full((rank, 2), float(rank), np.float32),
                      name="e.ag_some")
    assert np.asarray(g).shape == (sum(range(size)), 2)
    offset = 0
    for r in range(size):
        np.testing.assert_allclose(np.asarray(g)[offset:offset + r],
                                   float(r))
        offset += r

    b = hvd.broadcast(np.empty(0, np.float64), root_rank=size - 1,
                      name="e.bc0")
    assert np.asarray(b).shape == (0,)

    # the world still works afterwards
    out = hvd.allreduce(np.full(5, float(rank + 1), np.float32),
                        average=False, name="e.after")
    np.testing.assert_allclose(out, sum(range(1, size + 1)))


def scenario_mixed_op_storm(hvd, rank, size):
    """30 mixed collectives submitted asynchronously in a DIFFERENT
    random order on every rank: the coordinator must serialize them
    into one agreed schedule and complete every handle with the right
    value — the core negotiation promise (reference spirit:
    test_torch.py's out-of-order and partial-participation legs)."""
    rng = np.random.RandomState(1000 + rank)  # per-rank order!
    ssum = sum(range(1, size + 1))

    jobs = []
    for i in range(10):
        jobs.append(("ar", i))
        jobs.append(("bc", i))
        jobs.append(("ag", i))
    order = rng.permutation(len(jobs))

    handles = {}
    for idx in order:
        kind, i = jobs[idx]
        if kind == "ar":
            handles[("ar", i)] = hvd.allreduce_async(
                np.full(64 + i, float(rank + 1) * (i + 1), np.float64),
                average=False, name=f"storm.ar{i}")
        elif kind == "bc":
            handles[("bc", i)] = hvd.broadcast_async(
                np.full(8, float(rank * 100 + i), np.float32),
                root_rank=i % size, name=f"storm.bc{i}")
        else:
            handles[("ag", i)] = hvd.allgather_async(
                np.full((rank + 1, 2), float(rank * 10 + i),
                        np.float32), name=f"storm.ag{i}")

    for i in range(10):
        np.testing.assert_allclose(
            hvd.synchronize(handles[("ar", i)]), ssum * (i + 1))
        np.testing.assert_allclose(
            hvd.synchronize(handles[("bc", i)]),
            float((i % size) * 100 + i))
        g = hvd.synchronize(handles[("ag", i)])
        assert np.asarray(g).shape == (sum(r + 1 for r in range(size)),
                                       2)
        offset = 0
        for r in range(size):
            np.testing.assert_allclose(
                np.asarray(g)[offset:offset + r + 1],
                float(r * 10 + i))
            offset += r + 1


def scenario_grouped_allreduce(hvd, rank, size):
    """grouped_allreduce: one call, many tensors, derived names agreed
    across ranks; mixed dtypes split into separate fusion batches but
    every member completes with exact values. The blocking form drains
    every member even when one errors (all-or-nothing surfacing)."""
    from horovod_tpu.common.status import HorovodInternalError

    ssum = sum(range(1, size + 1))
    tensors = [np.full(16 + i, float(rank + 1) * (i + 1), np.float64)
               for i in range(6)]
    tensors.append(np.full(4, rank + 1, np.int64))  # dtype break
    outs = hvd.grouped_allreduce(tensors, average=False, name="grp")
    for i in range(6):
        np.testing.assert_allclose(outs[i],
                                   np.full(16 + i, ssum * (i + 1.0)))
    np.testing.assert_allclose(np.asarray(outs[6], np.float64),
                               float(ssum))

    # average semantics apply per member
    avg = hvd.grouped_allreduce(
        [np.full(3, float(rank + 1) * 2, np.float32)], name="grp.avg")
    np.testing.assert_allclose(avg[0], 2.0 * ssum / size)

    # all-or-nothing: one member mismatched in shape across ranks ->
    # the group call raises, the good members still completed
    bad = [np.ones(5, np.float32),
           np.ones(4 + rank % 2, np.float32)]  # member 1 mismatches
    try:
        hvd.grouped_allreduce(bad, average=False, name="grp.bad")
    except HorovodInternalError as e:
        assert "shape" in str(e).lower()
    else:
        if size > 1:
            raise AssertionError("expected group member error")
    # the world remains usable
    ok = hvd.grouped_allreduce([np.ones(2, np.float32)],
                               average=False, name="grp.after")
    np.testing.assert_allclose(ok[0], float(size))

    # pre-validation: an unscalable member (int under Average) fails
    # the WHOLE call before anything is enqueued — no half-submitted
    # group for peers to block on
    try:
        hvd.grouped_allreduce([np.ones(2, np.float32),
                               np.ones(2, np.int32)], name="grp.val")
    except ValueError:
        pass
    else:
        raise AssertionError("expected ValueError for int average")
    ok = hvd.grouped_allreduce([np.ones(2, np.float32)],
                               average=False, name="grp.after2")
    np.testing.assert_allclose(ok[0], float(size))

    # pre-validation also covers unsupported DTYPES: a complex member
    # must fail the whole call before member 0 is enqueued (otherwise
    # member 0 would be left in flight and peers would hang on it)
    try:
        hvd.grouped_allreduce([np.ones(2, np.float32),
                               np.ones(2, np.complex64)],
                              average=False, name="grp.cplx")
    except ValueError:
        pass
    else:
        raise AssertionError("expected ValueError for complex dtype")
    ok = hvd.grouped_allreduce([np.ones(2, np.float32)],
                               average=False, name="grp.after3")
    np.testing.assert_allclose(ok[0], float(size))


def _record_batches(hvd):
    """Wrap the runtime's op dispatch to record every executed batch as
    (response_type_name, [tensor_names]) — lets scenarios assert HOW
    work was batched, not just that values are right."""
    from horovod_tpu.common import basics as _b
    rt = _b.runtime()
    seen = []
    orig = rt.op_manager.execute

    def wrapped(entries, response):
        seen.append((response.response_type.name,
                     list(response.tensor_names)))
        return orig(entries, response)

    rt.op_manager.execute = wrapped
    return seen


def scenario_fused_allgather(hvd, rank, size):
    """ALLGATHER responses fuse under the threshold like allreduce
    (reference: operations.cc:1172-1234): several small allgathers
    submitted together execute as multi-entry batches on every
    backend, with entry-major displacement unpack and variable dim-0
    per rank preserved per entry."""
    seen = _record_batches(hvd)

    handles, specs = [], []
    for i in range(6):
        # distinct slice shapes AND variable dim-0 per rank
        rows = rank + 1 + (i % 2)
        x = np.full((rows, i + 1), float(rank * 10 + i), np.float32)
        specs.append((rows, i + 1))
        handles.append(hvd.allgather_async(x, name=f"fag.{i}"))
    for i, h in enumerate(handles):
        out = hvd.synchronize(h)
        total_rows = sum(r + 1 + (i % 2) for r in range(size))
        assert out.shape == (total_rows, i + 1), (i, out.shape)
        off = 0
        for r in range(size):
            rr = r + 1 + (i % 2)
            np.testing.assert_allclose(
                out[off:off + rr], np.full((rr, i + 1),
                                           float(r * 10 + i)))
            off += rr

    ag_batches = [names for kind, names in seen if kind == "ALLGATHER"]
    assert any(len(b) >= 2 for b in ag_batches), \
        f"no fused allgather batch executed: {ag_batches}"

    # an int64 allgather must NOT fuse into a float32 batch
    seen.clear()
    h1 = hvd.allgather_async(np.full((2, 2), rank, np.float32),
                             name="fag.f32")
    h2 = hvd.allgather_async(np.full((2, 2), rank, np.int64),
                             name="fag.i64")
    hvd.synchronize(h1), hvd.synchronize(h2)
    for kind, names in seen:
        if kind == "ALLGATHER" and len(names) > 1:
            raise AssertionError(f"mixed-dtype allgather fused: {names}")

    # empty entries INSIDE a fused batch: one entry empty on every
    # rank, one empty on rank 0 only, one normal — displacement math
    # must keep zero-length components straight
    he = [hvd.allgather_async(np.empty((0, 3), np.float32),
                              name="fag.e.all"),
          hvd.allgather_async(np.full((rank, 3), float(rank),
                                      np.float32), name="fag.e.some"),
          hvd.allgather_async(np.full((2, 3), float(rank + 10),
                                      np.float32), name="fag.e.full")]
    out = hvd.synchronize(he[0])
    assert out.shape == (0, 3), out.shape
    out = hvd.synchronize(he[1])
    assert out.shape == (sum(range(size)), 3)
    off = 0
    for r in range(size):
        np.testing.assert_allclose(out[off:off + r], float(r))
        off += r
    out = hvd.synchronize(he[2])
    assert out.shape == (2 * size, 3)
    for r in range(size):
        np.testing.assert_allclose(out[2 * r:2 * r + 2], float(r + 10))


def scenario_sparse_allgather_fusion(hvd, rank, size):
    """The sparse-gradient traffic shape (TF IndexedSlices -> one
    values + one indices allgather per embedding tensor, the word2vec
    path): with allgather fusion, a step's 6 tensor pairs execute as
    ~2 fused batches (f32 values together, i64 indices together)
    instead of 12 negotiated singles (reference bar:
    operations.cc:1172-1234)."""
    seen = _record_batches(hvd)
    n_tensors = 6
    handles = []
    for t in range(n_tensors):
        rows = rank + 1 + t % 3
        handles.append((t, "v", hvd.allgather_async(
            np.full((rows, 8), float(rank * 10 + t), np.float32),
            name=f"sp.{t}.values")))
        handles.append((t, "i", hvd.allgather_async(
            np.arange(rows, dtype=np.int64) + rank * 100,
            name=f"sp.{t}.indices")))
    for t, kind, h in handles:
        out = np.asarray(hvd.synchronize(h))
        rows = [r + 1 + t % 3 for r in range(size)]
        assert out.shape[0] == sum(rows), (t, kind, out.shape)
        off = 0
        for r in range(size):
            if kind == "v":
                np.testing.assert_allclose(out[off:off + rows[r]],
                                           float(r * 10 + t))
            else:
                np.testing.assert_array_equal(
                    out[off:off + rows[r]],
                    np.arange(rows[r], dtype=np.int64) + r * 100)
            off += rows[r]
    batches = [names for k, names in seen if k == "ALLGATHER"]
    total = sum(len(b) for b in batches)
    assert total == 2 * n_tensors, (total, batches)
    # the whole step must collapse into a few fused batches, not one
    # negotiation+dispatch per tensor (cycle straddles may split once)
    assert len(batches) <= 6, [sorted(b) for b in batches]
    assert any(len(b) >= 3 for b in batches), batches


def scenario_grouped_atomic(hvd, rank, size):
    """Grouped allreduce atomicity is a guarantee, not best-effort:
    all members land in ONE fused response even with the default
    1 ms cycle ticking concurrently and another thread spamming its
    own singles (Runtime.enqueue_group holds the table lock across
    the whole insert)."""
    import threading

    seen = _record_batches(hvd)

    def spam():
        # Fixed count on every rank: a collective only some ranks
        # submit would deadlock the world (blocking allreduce paces
        # all ranks through the same 50 names).
        for i in range(50):
            hvd.allreduce(np.full(8, float(rank + 1), np.float32),
                          average=False, name=f"spam.{i}")

    spammer = threading.Thread(target=spam)
    spammer.start()
    try:
        for round_ in range(5):
            group = [np.full(16, float(rank + 1) * (i + 1), np.float32)
                     for i in range(8)]
            outs = hvd.grouped_allreduce(group, average=False,
                                         name=f"atom.{round_}")
            ssum = sum(range(1, size + 1))
            for i, o in enumerate(outs):
                np.testing.assert_allclose(o, ssum * (i + 1.0))
            want = {f"atom.{round_}.{i}" for i in range(8)}
            batches = [set(names) for kind, names in seen
                       if kind == "ALLREDUCE"]
            containing = [b for b in batches if b & want]
            assert len(containing) == 1 and want <= containing[0], \
                f"group {round_} split across batches: " \
                f"{[sorted(b & want) for b in containing]}"
    finally:
        spammer.join()
    # spam thread's own collectives must drain before shutdown
    hvd.barrier(name="atom.done")


def scenario_coordinator_fuzz(hvd, rank, size):
    """Randomized negotiation fuzz — the framework's race-detection
    analog (SURVEY §5: the coordinator protocol is what turns racy
    per-rank op ordering into a total order). A seeded job list of a
    few hundred mixed collectives (all 5 data ops × 4 dtypes × varied
    shapes, interleaved barriers) is submitted asynchronously in a
    DIFFERENT random order on every rank, in waves with partial drains
    so negotiation, fusion, and execution overlap; every handle's value
    is checked exactly."""
    jobs_rng = np.random.RandomState(4242)        # SAME on all ranks
    order_rng = np.random.RandomState(977 + rank)  # per-rank order
    ssum = sum(range(1, size + 1))
    dtypes = [np.float32, np.float64, np.int32, np.int64]

    jobs = []
    for i in range(240):
        kind = ["ar", "bc", "ag", "rs", "a2a"][jobs_rng.randint(5)]
        dt = dtypes[jobs_rng.randint(len(dtypes))]
        n = int(jobs_rng.randint(1, 90))
        root = int(jobs_rng.randint(size))
        jobs.append((i, kind, dt, n, root))

    def submit(job):
        i, kind, dt, n, root = job
        tag = f"fz.{i}"
        if kind == "ar":
            return hvd.allreduce_async(
                np.full(n, dt(rank + 1) * (i % 7 + 1), dt),
                average=False, name=tag)
        if kind == "bc":
            return hvd.broadcast_async(
                np.full(n, dt(rank * 100 + i), dt), root_rank=root,
                name=tag)
        if kind == "ag":
            return hvd.allgather_async(
                np.full((rank + 1, n), dt(rank * 10 + i), dt), name=tag)
        if kind == "rs":
            return hvd.reducescatter_async(
                (np.arange(size * n) + rank).astype(dt), name=tag)
        return hvd.alltoall_async(
            np.full((size * 2, n), dt(rank + i), dt), name=tag)

    def check(job, out):
        i, kind, dt, n, root = job
        out = np.asarray(out)
        if kind == "ar":
            np.testing.assert_allclose(
                out.astype(np.float64),
                np.full(n, float(ssum * (i % 7 + 1))))
        elif kind == "bc":
            np.testing.assert_allclose(
                out.astype(np.float64), float(root * 100 + i))
        elif kind == "ag":
            assert out.shape == (sum(r + 1 for r in range(size)), n)
            off = 0
            for r in range(size):
                np.testing.assert_allclose(
                    out[off:off + r + 1].astype(np.float64),
                    float(r * 10 + i))
                off += r + 1
        elif kind == "rs":
            base = size * np.arange(size * n) + sum(range(size))
            np.testing.assert_allclose(
                out.astype(np.float64),
                base[rank * n:(rank + 1) * n].astype(np.float64))
        else:
            assert out.shape == (size * 2, n)
            for r in range(size):
                np.testing.assert_allclose(
                    out[r * 2:(r + 1) * 2].astype(np.float64),
                    float(r + i))

    # waves with partial drains: in-flight ops from wave k overlap
    # wave k+1's negotiation
    pending = []
    for start in range(0, len(jobs), 60):
        wave = [jobs[j] for j in
                start + order_rng.permutation(
                    min(60, len(jobs) - start))]
        pending.extend((job, submit(job)) for job in wave)
        # Barrier decisions come from the SHARED rng: a collective only
        # some ranks submit would deadlock the world (which is exactly
        # what the stall inspector exists to report, but not what this
        # scenario tests).
        if jobs_rng.rand() < 0.5:
            hvd.barrier(name=f"fz.bar.{start}")
        # a grouped wave (shared decision + shared member count) rides
        # the same storm: atomic submission must hold under overlap
        if jobs_rng.rand() < 0.5:
            k = int(jobs_rng.randint(2, 7))
            gouts = hvd.grouped_allreduce(
                [np.full(12, float(rank + 1) * (m + 1), np.float32)
                 for m in range(k)],
                average=False, name=f"fz.grp.{start}")
            for m, o in enumerate(gouts):
                np.testing.assert_allclose(o, ssum * (m + 1.0))
        drain, pending = pending[:len(pending) // 2], \
            pending[len(pending) // 2:]
        for job, h in drain:
            check(job, hvd.synchronize(h))
    for job, h in pending:
        check(job, hvd.synchronize(h))


def _cache_runtime_stats(hvd):
    from horovod_tpu.common import basics as _b
    return _b.runtime().negotiation_cache_stats()


def _cache_fingerprint_crc(hvd) -> int:
    """CRC of the response cache's world-coherent state (slot map, LRU
    order, epoch) — allgathered across ranks to prove the caches
    marched in lockstep (Python hash() is process-seeded, crc32 is
    not)."""
    import zlib
    from horovod_tpu.common import basics as _b
    cache = _b.runtime()._cache
    return zlib.crc32(repr(cache.state_fingerprint()).encode())


def _assert_cache_coherent(hvd, rank, size, tag):
    """Every rank's cache fingerprint must be identical right now."""
    fp = _cache_fingerprint_crc(hvd)
    got = np.asarray(hvd.allgather(
        np.asarray([[fp]], np.int64), name=f"{tag}.fp"))
    assert (got == fp).all(), \
        f"rank {rank}: cache state diverged across ranks: {got.ravel()}"


def scenario_response_cache_steady(hvd, rank, size):
    """The steady-state negotiation fast path, end to end: a training-
    shaped loop resubmitting the same tensor set must (a) return exact
    values every step, (b) negotiate via the bitmask path (hit rate
    ~100%, fully cached cycles observed), (c) keep the cache state
    bit-identical across every rank, (d) invalidate coherently on
    shape and dtype changes and renegotiate exactly, and (e) survive
    skewed submission (a rank holding back a cached tensor: the
    others' hits stay queued un-granted until the straggler arrives)."""
    import time
    from horovod_tpu.common import basics as _b

    ssum = sum(range(1, size + 1))
    names = [f"rc.{i}" for i in range(8)]
    xs = [np.full(64 + i, float(rank + 1) * (i + 1), np.float64)
          for i in range(8)]

    def step(check=True):
        hs = [hvd.allreduce_async(x, average=False, name=nm)
              for x, nm in zip(xs, names)]
        for i, h in enumerate(hs):
            out = hvd.synchronize(h)
            if check:
                np.testing.assert_allclose(out, ssum * (i + 1.0))

    for _ in range(3):
        step()
    hvd.barrier(name="rc.bar")
    s0 = _cache_runtime_stats(hvd)
    assert s0["enabled"], "cache must be on by default"
    for _ in range(30):
        step()
    s1 = _cache_runtime_stats(hvd)
    d_hits = s1["hits"] - s0["hits"]
    d_misses = s1["misses"] - s0["misses"]
    rate = d_hits / max(1, d_hits + d_misses)
    assert rate >= 0.99, (rank, d_hits, d_misses, rate)
    assert s1["cached_cycles"] > s0["cached_cycles"], (rank, s0, s1)
    if os.environ.get("HOROVOD_TPU_SHM") == "0" \
            and os.environ.get("HOROVOD_CACHE_SPECULATIVE", "1") != "0":
        # Socket star data plane: the steady allreduce set must ride
        # the fused speculative round (shm/ring-bound batches keep
        # their own plane and legitimately never speculate).
        assert s1["spec_cycles"] > s0["spec_cycles"], (rank, s0, s1)
    _assert_cache_coherent(hvd, rank, size, "rc.a")

    # (d) SHAPE change: same names, new shapes -> slot invalidated on
    # every rank, renegotiated exactly, then hits resume
    xs = [np.full((3, 32 + i), float(rank + 1) * (i + 1), np.float64)
          for i in range(8)]
    step()
    _assert_cache_coherent(hvd, rank, size, "rc.b")
    s2 = _cache_runtime_stats(hvd)
    step()
    s3 = _cache_runtime_stats(hvd)
    assert s3["hits"] - s2["hits"] >= 8, (rank, s2, s3)  # hits resumed

    # DTYPE change on one tensor: only that slot invalidates
    xs[0] = np.full((3, 32), float(rank + 1), np.float32)
    step()
    _assert_cache_coherent(hvd, rank, size, "rc.c")
    step()

    # (e) skewed submission: every rank submits the cached rc.0 but
    # rank size-1 holds back for a while -- the others' hit bits stay
    # queued (requeued each cycle, never granted) until it arrives
    if rank == size - 1:
        time.sleep(0.4)
    out = hvd.allreduce(xs[0], average=False, name=names[0])
    np.testing.assert_allclose(np.asarray(out, np.float64), ssum * 1.0)
    _assert_cache_coherent(hvd, rank, size, "rc.d")

    # the world is fully usable afterwards (fresh names, full path)
    out = hvd.allreduce(np.full(5, float(rank + 1), np.float32),
                        average=False, name="rc.fresh")
    np.testing.assert_allclose(out, ssum)


def scenario_response_cache_hetero_spec(hvd, rank, size):
    """HOROVOD_CACHE_SPECULATIVE disagreeing across ranks (rank 1 has
    it off — set by the pytest wrapper) must stay CORRECT: speculation
    is per-cycle opportunistic, so the coordinator simply never sees a
    unanimous speculative cycle and every step rides the classic
    two-round cached path. Values stay exact, hits still accrue, and
    no rank ever completes a fused speculative cycle."""
    ssum = sum(range(1, size + 1))
    xs = [np.full(32, float(rank + 1) * (i + 1), np.float64)
          for i in range(6)]
    for _ in range(20):
        hs = [hvd.allreduce_async(x, average=False, name=f"hs.{i}")
              for i, x in enumerate(xs)]
        for i, h in enumerate(hs):
            np.testing.assert_allclose(hvd.synchronize(h),
                                       ssum * (i + 1.0))
    stats = _cache_runtime_stats(hvd)
    assert stats["cached_cycles"] > 0, (rank, stats)
    assert stats["spec_cycles"] == 0, (rank, stats)
    # and the spec-on ranks UNLEARN: after a few classically-answered
    # full grants the mask stops bidding, so the steady state is not
    # paying a wasted fused payload every cycle forever
    assert stats["spec_bids"] <= 8, (rank, stats)
    _assert_cache_coherent(hvd, rank, size, "hs.fp")


def scenario_native_steady(hvd, rank, size):
    """Zero-copy native steady cycle end to end (socket star; shm off
    and metrics armed by the pytest wrapper): a steady grouped-
    allreduce loop must (a) return exact sums every step, (b) complete
    steps through hvd_steady_worker/coord (native_steady_cycles
    advancing on every rank), (c) perform ZERO fallback byte-object
    copies on the data plane once steady (hvd_data_copies_total delta
    == 0 — the O(1)-allocations acceptance property), and (d) honor
    the aliasing contract: results returned at step k are never
    clobbered by later steps, and stay independently mutable."""
    from horovod_tpu import native as _nat

    ssum = sum(range(1, size + 1))
    xs = [np.full(128 + i, float(rank + 1) * (i + 1), np.float64)
          for i in range(8)]

    def step():
        hs = hvd.grouped_allreduce_async(xs, average=False, name="zc")
        return [np.asarray(hvd.synchronize(h)) for h in hs]

    for _ in range(4):
        step()
    hvd.barrier(name="zc.bar")
    s0 = _cache_runtime_stats(hvd)
    c0 = hvd.metrics()["local"].get("hvd_data_copies_total",
                                    {"v": 0.0})["v"]
    held = kept = None
    for it in range(25):
        res = step()
        for i, r in enumerate(res):
            np.testing.assert_allclose(r, ssum * (i + 1.0))
        if it == 5:
            kept = res                       # live views from step 5
            held = [r.copy() for r in res]   # their frozen values
    for a, b in zip(kept, held):
        np.testing.assert_array_equal(a, b)  # 19 later steps: intact
    kept[0] += 1000.0                        # outputs stay writable...
    res = step()
    for i, r in enumerate(res):              # ...and never feed back
        np.testing.assert_allclose(r, ssum * (i + 1.0))
    s1 = _cache_runtime_stats(hvd)
    c1 = hvd.metrics()["local"].get("hvd_data_copies_total",
                                    {"v": 0.0})["v"]
    assert s1["cached_cycles"] > s0["cached_cycles"] \
        or s1["spec_cycles"] > s0["spec_cycles"], (rank, s0, s1)
    native_on = (_nat.get() is not None
                 and os.environ.get("HOROVOD_TPU_ZERO_COPY", "1")
                 != "0")
    if os.environ.get("HOROVOD_TPU_SHM") == "0":
        # Socket star: the steady set rides the fused speculative
        # round; with the native core loaded, as ONE C call per step.
        assert s1["spec_cycles"] > s0["spec_cycles"], (rank, s0, s1)
        if native_on:
            assert s1["native_steady_cycles"] \
                > s0["native_steady_cycles"], (rank, s0, s1)
    if native_on:
        # The acceptance property: after warmup, steady steps perform
        # zero fallback byte-object copies on the data plane — on the
        # shm AND socket backends.
        assert c1 - c0 == 0, (rank, c0, c1)
    _assert_cache_coherent(hvd, rank, size, "zc.fp")


def scenario_native_hetero(hvd, rank, size):
    """Heterogeneous native worlds (the pytest wrapper turns the
    native core / zero-copy knob OFF on a subset of ranks): the
    CACHED_SPEC wire format is byte-identical whether a rank
    serializes in Python or sends iovecs from the arena, so mixed
    worlds must stay EXACT and still complete fused speculative
    cycles — and a native coordinator keeps its one-call steady loop
    even when some peers are pure Python."""
    from horovod_tpu import native as _nat

    ssum = sum(range(1, size + 1))
    xs = [np.full(96, float(rank + 1) * (i + 1), np.float64)
          for i in range(6)]
    for _ in range(4):
        hs = hvd.grouped_allreduce_async(xs, average=False, name="nh")
        for h in hs:
            hvd.synchronize(h)
    hvd.barrier(name="nh.bar")
    s0 = _cache_runtime_stats(hvd)
    for _ in range(20):
        hs = hvd.grouped_allreduce_async(xs, average=False, name="nh")
        for i, h in enumerate(hs):
            np.testing.assert_allclose(hvd.synchronize(h),
                                       ssum * (i + 1.0))
    s1 = _cache_runtime_stats(hvd)
    assert s1["spec_cycles"] > s0["spec_cycles"], (rank, s0, s1)
    if rank == 0 and _nat.get() is not None \
            and os.environ.get("HOROVOD_TPU_ZERO_COPY", "1") != "0":
        # the coordinator runs natively even over pure-Python peers
        assert s1["native_steady_cycles"] > s0["native_steady_cycles"], \
            (rank, s0, s1)
    _assert_cache_coherent(hvd, rank, size, "nh.fp")


def scenario_overlap_steady(hvd, rank, size):
    """Overlap tier end to end (HOROVOD_OVERLAP_* armed by the pytest
    wrapper): a bucketed grouped-allreduce training loop must
    (a) return exact sums every step, (b) split each step into
    multiple buckets (hvd_overlap_buckets_total advancing) that each
    learn their own steady mask, (c) complete steady cycles through
    the in-flight overlap runner (overlap_cycles advancing), and
    (d) preserve the zero-copy property: hvd_data_copies_total does
    not move once steady. With HOROVOD_COMPRESSION=bf16 the values
    here are small integers (exactly representable), so compression
    (and the chunked native send with a small
    HOROVOD_OVERLAP_CHUNK_BYTES) keeps the asserts exact."""
    from horovod_tpu import native as _nat
    from horovod_tpu.common import basics as _b

    ssum = sum(range(1, size + 1))
    xs = [np.full(192 + 16 * i, float(rank + 1) * (i + 1), np.float32)
          for i in range(16)]

    def step():
        hs = hvd.grouped_allreduce_async(xs, average=False, name="ov")
        return [np.asarray(hvd.synchronize(h)) for h in hs]

    for _ in range(8):
        step()  # warmup: every bucket learns its steady mask
    hvd.barrier(name="ov.bar")
    s0 = _cache_runtime_stats(hvd)
    c0 = hvd.metrics()["local"].get("hvd_data_copies_total",
                                    {"v": 0.0})["v"]
    for it in range(25):
        res = step()
        for i, r in enumerate(res):
            np.testing.assert_allclose(r, ssum * (i + 1.0))
    s1 = _cache_runtime_stats(hvd)
    c1 = hvd.metrics()["local"].get("hvd_data_copies_total",
                                    {"v": 0.0})["v"]
    rt = _b.runtime()
    k = int(os.environ.get("HOROVOD_OVERLAP_BUCKETS", "0"))
    if k > 1:
        # bucketed dispatch engaged: the submission really split
        m = hvd.metrics()["local"]
        assert m.get("hvd_overlap_buckets_total",
                     {"v": 0.0})["v"] > 0, m
        # each bucket holds its own steady mask
        assert len(rt._steady) >= 2, (rank, len(rt._steady))
    native_on = (_nat.get() is not None
                 and os.environ.get("HOROVOD_TPU_ZERO_COPY", "1")
                 != "0")
    if native_on and int(os.environ.get(
            "HOROVOD_OVERLAP_INFLIGHT", "0")) > 0:
        # in-flight cycles engaged and zero-copy preserved
        assert s1["overlap_cycles"] > s0["overlap_cycles"], (
            rank, s0, s1)
        assert c1 - c0 == 0, (rank, c0, c1)
    assert s1["cached_cycles"] > s0["cached_cycles"] \
        or s1["spec_cycles"] > s0["spec_cycles"], (rank, s0, s1)
    _assert_cache_coherent(hvd, rank, size, "ov.fp")


def scenario_overlap_bitexact(hvd, rank, size):
    """Bucketed training must be BIT-exact vs an unbucketed replay:
    run the same deterministic step stream twice in one world — first
    with the wrapper-armed bucket knobs, then with bucketing turned
    off on every rank at the same point — and require bitwise-equal
    outputs. Values are rounding-sensitive f32 fractions, so any
    reduction-order change WOULD show: bucketing only moves fused
    batch boundaries, never the per-element rank-ascending sum."""
    from horovod_tpu.common import basics as _b

    xs = [np.full(128 + 8 * i, 0.1 * (rank + 1) * (i + 1), np.float32)
          for i in range(12)]

    def phase(tag, steps=10):
        outs = None
        for _ in range(steps):
            hs = hvd.grouped_allreduce_async(xs, average=False,
                                             name=f"bx.{tag}")
            outs = [np.asarray(hvd.synchronize(h)) for h in hs]
        return outs

    a = phase("bucketed")
    hvd.barrier(name="bx.bar")
    # Same point on every rank: later submissions stop bucketing.
    _b.runtime().config.overlap_buckets = 0
    _b.runtime().config.overlap_bucket_bytes = 0
    b = phase("flat")
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)
    _assert_cache_coherent(hvd, rank, size, "bx.fp")


def scenario_overlap_hetero(hvd, rank, size):
    """Heterogeneous HOROVOD_OVERLAP_* knobs (set per-rank by the
    pytest wrapper): ranks disagree on bucket count and in-flight
    depth, so per-cycle hit masks differ — grants degrade to the
    intersection, speculation backs off where peers answer
    classically, and the world must stay EXACT and cache-coherent
    (degrade-to-synchronous, never diverge)."""
    ssum = sum(range(1, size + 1))
    xs = [np.full(160 + 8 * i, float(rank + 1) * (i + 1), np.float32)
          for i in range(12)]
    for _ in range(20):
        hs = hvd.grouped_allreduce_async(xs, average=False, name="oh")
        res = [np.asarray(hvd.synchronize(h)) for h in hs]
        for i, r in enumerate(res):
            np.testing.assert_allclose(r, ssum * (i + 1.0))
    _assert_cache_coherent(hvd, rank, size, "oh.fp")


def scenario_overlap_sigkill(hvd, rank, size):
    """SIGKILL a rank while buckets are IN FLIGHT on the overlap
    runner (fault spec fires at an op index deep in bucketed steady
    state): survivors must raise WorldAbortedError naming the dead
    rank within the heartbeat deadline — the PR 2 fail-fast invariant
    holds when the native cycle runs on the completion thread."""
    import time
    from horovod_tpu.common.status import WorldAbortedError

    victim = 1
    deadline = float(os.environ["HOROVOD_HEARTBEAT_TIMEOUT"]) + 12.0
    xs = [np.full(128 + 8 * i, float(rank + 1), np.float32)
          for i in range(16)]
    t0 = time.monotonic()
    aborted = None
    while True:
        try:
            hs = hvd.grouped_allreduce_async(xs, average=False,
                                             name="ok.steady")
            for h in hs:
                hvd.synchronize(h)
        except WorldAbortedError as e:
            aborted = e
            break
        assert time.monotonic() - t0 < deadline, (
            f"rank {rank}: collectives kept succeeding {deadline}s "
            f"after the fault")
    assert aborted.origin_rank == victim, (rank, str(aborted))
    assert f"rank {victim}" in str(aborted), str(aborted)
    assert time.monotonic() - t0 < deadline
    stats = _cache_runtime_stats(hvd)
    assert stats["cached_cycles"] >= 5 or stats["spec_cycles"] >= 5, \
        stats
    hvd.shutdown()


def scenario_overlap_sever(hvd, rank, size):
    """Severed control link mid-overlapped-cycle: rank 1's upward
    channel closes while the overlap runner drives native cycles;
    survivors must abort with a structured WorldAbortedError within
    the deadline (the runner's parked transport error feeds the same
    world-convergent blame path as the synchronous one)."""
    import time
    from horovod_tpu.common.status import WorldAbortedError

    deadline = float(os.environ["HOROVOD_HEARTBEAT_TIMEOUT"]) + 12.0
    xs = [np.full(128, float(rank + 1), np.float32) for _ in range(8)]
    t0 = time.monotonic()
    while True:
        try:
            hs = hvd.grouped_allreduce_async(xs, average=False,
                                             name="os.steady")
            for h in hs:
                hvd.synchronize(h)
        except WorldAbortedError as e:
            assert e.origin_rank >= -1, str(e)
            break
        assert time.monotonic() - t0 < deadline, (
            f"rank {rank}: collectives kept succeeding {deadline}s "
            f"after the sever")
    assert time.monotonic() - t0 < deadline
    hvd.shutdown()


def scenario_abort_sigkill_native_steady(hvd, rank, size):
    """SIGKILL a rank squarely mid-NATIVE-steady-cycle (fault spec
    fires at an op index reached deep in zero-copy steady state, so
    survivors are blocked inside hvd_steady_worker/coord when the
    victim dies): the C loop must honor the armed recv deadlines and
    surface the PR 2 fail-fast invariant — every survivor raises
    WorldAbortedError naming the dead rank within the heartbeat
    deadline."""
    import time
    from horovod_tpu.common.status import WorldAbortedError

    victim = 1
    deadline = float(os.environ["HOROVOD_HEARTBEAT_TIMEOUT"]) + 12.0
    x = np.full(256, float(rank + 1), np.float64)
    t0 = time.monotonic()
    aborted = None
    while True:
        try:
            hvd.allreduce(x, average=False, name="zk.steady")
        except WorldAbortedError as e:
            aborted = e
            break
        assert time.monotonic() - t0 < deadline, (
            f"rank {rank}: collectives kept succeeding {deadline}s "
            f"after the fault")
    assert aborted.origin_rank == victim, (rank, str(aborted))
    assert f"rank {victim}" in str(aborted), str(aborted)
    assert time.monotonic() - t0 < deadline
    stats = _cache_runtime_stats(hvd)
    from horovod_tpu import native as _nat
    if _nat.get() is not None:
        # the kill really did land in zero-copy steady state
        assert stats["native_steady_cycles"] >= 5, stats
    try:
        hvd.allreduce(x, average=False, name="zk.post")
        raise AssertionError("enqueue after world abort must fail")
    except WorldAbortedError as e:
        assert e.origin_rank == victim, str(e)
    hvd.shutdown()


def scenario_abort_sever_native_steady(hvd, rank, size):
    """Severed control link mid-native-steady-cycle (fault injection
    closes rank 1's upward channel at a deep cycle index): survivors
    must abort with a structured WorldAbortedError within the
    deadline — the native loop's transport errors feed the same
    world-convergent blame path as the Python one."""
    import time
    from horovod_tpu.common.status import WorldAbortedError

    deadline = float(os.environ["HOROVOD_HEARTBEAT_TIMEOUT"]) + 12.0
    x = np.full(256, float(rank + 1), np.float64)
    t0 = time.monotonic()
    aborted = None
    while True:
        try:
            hvd.allreduce(x, average=False, name="zs.steady")
        except WorldAbortedError as e:
            aborted = e
            break
        assert time.monotonic() - t0 < deadline, (
            f"rank {rank}: collectives kept succeeding {deadline}s "
            f"after the sever")
    assert aborted.origin_rank >= -1, str(aborted)
    assert time.monotonic() - t0 < deadline
    hvd.shutdown()


def scenario_response_cache_eviction(hvd, rank, size):
    """Capacity eviction under a tiny HOROVOD_CACHE_CAPACITY (set by
    the pytest wrapper): cycling through more distinct tensors than
    slots keeps evicting in LRU order — on every rank identically —
    and values stay exact throughout, including when an evicted name
    comes back (miss -> full renegotiation -> re-cached)."""
    cap = int(os.environ["HOROVOD_CACHE_CAPACITY"])
    ssum = sum(range(1, size + 1))
    n_names = cap * 3
    for wave in range(3):
        for i in range(n_names):
            out = hvd.allreduce(
                np.full(16, float(rank + 1) * (i + 1), np.float64),
                average=False, name=f"ev.{i}")
            np.testing.assert_allclose(out, ssum * (i + 1.0))
        _assert_cache_coherent(hvd, rank, size, f"ev.fp{wave}")
    stats = _cache_runtime_stats(hvd)
    assert stats["entries"] <= cap, stats
    # steady reuse of a WORKING set under capacity still gets hits
    s0 = _cache_runtime_stats(hvd)
    for _ in range(10):
        for i in range(max(1, cap // 2)):
            hvd.allreduce(np.full(8, float(rank + 1), np.float64),
                          average=False, name=f"ws.{i}")
    s1 = _cache_runtime_stats(hvd)
    assert s1["hits"] > s0["hits"], (s0, s1)
    _assert_cache_coherent(hvd, rank, size, "ev.fin")


def scenario_abort_sigkill_cached(hvd, rank, size):
    """SIGKILL a rank squarely mid-CACHED-cycle: fault injection fires
    at an op index reached deep in bitmask steady state, so the
    survivors are blocked in a bits-frame gather when the victim dies.
    They must still raise WorldAbortedError naming the dead rank within
    the heartbeat deadline (the PR 2 fail-fast invariant holds on the
    fast path), and handles enqueued afterwards must fail the same
    structured way."""
    import time
    from horovod_tpu.common.status import WorldAbortedError

    victim = 1
    deadline = float(os.environ["HOROVOD_HEARTBEAT_TIMEOUT"]) + 12.0
    x = np.full(64, float(rank + 1), np.float32)
    t0 = time.monotonic()
    i = 0
    aborted = None
    while True:
        try:
            # SAME name every iteration: after the first op the cycle
            # is pure bitmask — the fault (op=40) lands mid-fast-path
            hvd.allreduce(x, average=False, name="ck.steady")
        except WorldAbortedError as e:
            aborted = e
            break
        i += 1
        assert time.monotonic() - t0 < deadline, (
            f"rank {rank}: collectives kept succeeding {deadline}s "
            f"after the fault")
    assert aborted.origin_rank == victim, (rank, str(aborted))
    assert f"rank {victim}" in str(aborted), str(aborted)
    assert time.monotonic() - t0 < deadline
    # the kill really did land in cached steady state
    stats = _cache_runtime_stats(hvd)
    assert stats["cached_cycles"] >= 10, stats
    try:
        hvd.allreduce(x, average=False, name="ck.post")
        raise AssertionError("enqueue after world abort must fail")
    except WorldAbortedError as e:
        assert e.origin_rank == victim, str(e)
    hvd.shutdown()


def scenario_cache_byte_budget(hvd, rank, size):
    """Control-plane byte-budget regression guard: in bitmask steady
    state a cycle must move O(capacity/8) control bytes per rank —
    asserted through a counting wrapper on Channel.send/recv that
    tallies ONLY the control tags (TAG_REQUESTS/TAG_RESPONSES; data
    payloads and PINGs ride other tags). A regression that quietly
    re-serializes Request lists every cycle trips the per-cycle
    budget by an order of magnitude. The pytest wrapper disables
    HOROVOD_CACHE_SPECULATIVE: fused speculative frames deliberately
    carry the batch's tensor data on the request tag (that is the
    point — one round for grant AND data), so the mask-path budget is
    only measurable with speculation off."""
    from horovod_tpu.common import controller as _ctl
    from horovod_tpu.common import network as _net

    counts = {"bytes": 0}
    ctrl_tags = (_ctl.TAG_REQUESTS, _ctl.TAG_RESPONSES)
    orig_send, orig_recv = _net.Channel.send, _net.Channel.recv

    def send(self, payload, tag=0):
        if tag in ctrl_tags:
            counts["bytes"] += len(_net.as_byte_view(payload))
        return orig_send(self, payload, tag)

    def recv(self):
        tag, data = orig_recv(self)
        if tag in ctrl_tags:
            counts["bytes"] += len(data)
        return tag, data

    _net.Channel.send, _net.Channel.recv = send, recv
    hvd.init()
    from horovod_tpu.common import basics as _b
    rt = _b.runtime()

    capacity = int(os.environ["HOROVOD_CACHE_CAPACITY"])
    ssum = sum(range(1, size + 1))
    names = [f"bb.{i}" for i in range(16)]
    xs = [np.full(64, float(rank + 1) * (i + 1), np.float64)
          for i in range(16)]

    def step():
        hs = [hvd.allreduce_async(x, average=False, name=nm)
              for x, nm in zip(xs, names)]
        for h in hs:
            hvd.synchronize(h)

    for _ in range(5):
        step()
    hvd.barrier(name="bb.bar")
    bytes0, cycles0 = counts["bytes"], rt._cycle_count
    for _ in range(50):
        step()
    bytes1, cycles1 = counts["bytes"], rt._cycle_count
    stats = rt.negotiation_cache_stats()
    d_cycles = max(1, cycles1 - cycles0)
    per_cycle = (bytes1 - bytes0) / d_cycles
    # Worker budget: one bitmask request frame + one bitmask response
    # frame per cycle — two masks each plus fixed headers. The full
    # path for 16 tensors moves well over 1 KB per cycle.
    budget = 2 * ((capacity + 7) // 8) + 160
    if rank != 0:
        # rank 0's per-cycle frames ride the native fan-out, not
        # Channel.send/recv — the budget is asserted on workers, whose
        # Python channel is the steady-state path being guarded.
        assert per_cycle <= budget, (
            f"rank {rank}: steady-state control plane moved "
            f"{per_cycle:.0f} B/cycle (budget {budget} B with "
            f"HOROVOD_CACHE_CAPACITY={capacity}) — fast-path "
            f"regression")
    assert stats["hit_rate"] >= 0.95, stats
    # correctness spot check after all the counting
    out = hvd.allreduce(np.full(8, float(rank + 1), np.float64),
                        average=False, name="bb.check")
    np.testing.assert_allclose(out, ssum)
    _net.Channel.send, _net.Channel.recv = orig_send, orig_recv


scenario_cache_byte_budget.no_auto_init = True


def scenario_metrics_world(hvd, rank, size):
    """World-aggregated metrics plane end to end (HOROVOD_TPU_METRICS
    + interval + ephemeral port set by the pytest wrapper): a steady
    allreduce loop runs, every rank allgathers its LOCAL
    hvd_bytes_allreduced_total, and rank 0 polls its control-tree
    world aggregate until it equals the per-rank sum exactly — then
    scrapes the live Prometheus endpoint and asserts the text view
    agrees. Runs identically across shm / socket / hierarchical
    worlds (the hier wrapper proves local roots fold their host into
    one METRICS frame without losing counts)."""
    import time
    import urllib.request

    ssum = sum(range(1, size + 1))
    x = np.full(256, float(rank + 1), np.float64)
    steps = 20
    for _ in range(steps):
        out = hvd.allreduce(x, average=False, name="mw.steady")
        np.testing.assert_allclose(out, ssum)

    view = hvd.metrics()
    assert view["enabled"], view
    local = view["local"]["hvd_bytes_allreduced_total"]["v"]
    assert local == steps * x.nbytes, (rank, local, steps * x.nbytes)
    # Share the true per-rank totals over the data plane (allgather
    # moves bytes too, but not ALLREDUCE bytes — the counter under
    # test stays frozen from here on).
    got = np.asarray(hvd.allgather(
        np.asarray([[local]], np.float64), name="mw.locals"))
    expected_world = float(got.sum())

    if rank == 0:
        port = view["http_port"]
        assert port and port > 0, view
        deadline = time.monotonic() + 30.0
        world_v = None
        while time.monotonic() < deadline:
            world = hvd.metrics()["world"]
            world_v = world.get("hvd_bytes_allreduced_total",
                                {}).get("v")
            reporting = world.get("hvd_ranks_reporting", {}).get("v")
            if world_v == expected_world and reporting == size:
                break
            time.sleep(0.1)
        assert world_v == expected_world, (world_v, expected_world)
        # the live Prometheus endpoint must agree with the API view
        txt = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10
        ).read().decode()
        value_line = [l for l in txt.splitlines()
                      if l.startswith("hvd_bytes_allreduced_total ")]
        assert value_line, txt[:2000]
        assert float(value_line[0].split()[1]) == expected_world, \
            (value_line, expected_world)
        assert "# TYPE hvd_bytes_allreduced_total counter" in txt
        assert "hvd_negotiation_seconds_count" in txt
        assert "hvd_cycle_seconds_bucket" in txt
        if size > 1:
            assert "hvd_peer_heartbeat_age_seconds" in txt
    # hold the world together until rank 0 finished polling/scraping
    hvd.barrier(name="mw.done")


def scenario_metrics_sigkill(hvd, rank, size):
    """SIGKILL a rank mid-run WHILE rank 0 is being scraped (fault
    spec + metrics env set by the pytest wrapper): the metrics plane —
    out-of-band frames on the very channels the abort protocol
    watches — must not mask PR 2's fail-fast invariant. Survivors
    raise WorldAbortedError naming the dead rank within the heartbeat
    deadline, with a scraper thread hammering /metrics throughout."""
    import threading
    import time
    import urllib.request
    from horovod_tpu.common.status import WorldAbortedError

    victim = 1
    deadline_s = float(os.environ["HOROVOD_HEARTBEAT_TIMEOUT"]) + 12.0
    scrapes = []
    stop = threading.Event()
    if rank == 0:
        port = hvd.metrics()["http_port"]
        assert port and port > 0

        def _scrape_loop():
            while not stop.is_set():
                try:
                    txt = urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/metrics",
                        timeout=2).read().decode()
                    scrapes.append("hvd_cycles_total" in txt)
                except Exception:
                    pass
                time.sleep(0.05)

        t = threading.Thread(target=_scrape_loop, daemon=True)
        t.start()

    x = np.full(64, float(rank + 1), np.float32)
    t0 = time.monotonic()
    aborted = None
    while True:
        try:
            hvd.allreduce(x, average=False, name="ms.steady")
        except WorldAbortedError as e:
            aborted = e
            break
        assert time.monotonic() - t0 < deadline_s, (
            f"rank {rank}: collectives kept succeeding {deadline_s}s "
            f"after the fault")
    assert aborted.origin_rank == victim, (rank, str(aborted))
    assert f"rank {victim}" in str(aborted), str(aborted)
    if rank == 0:
        stop.set()
        assert scrapes and any(scrapes), \
            "no successful scrape while the world was live"
    try:
        hvd.allreduce(x, average=False, name="ms.post")
        raise AssertionError("enqueue after world abort must fail")
    except WorldAbortedError as e:
        assert e.origin_rank == victim, str(e)
    hvd.shutdown()


def scenario_trace_world(hvd, rank, size):
    """World trace plane e2e (ISSUE 11; env set by the pytest
    wrapper: HOROVOD_TPU_TRACE=<merged path>, metrics armed, short
    ping/trace intervals, speculation off so every recv rides the
    Python paths where PINGs close the clock loop, and a repeating
    ``delay`` fault making rank 2 a sustained straggler). A steady
    loop runs; rank 0 then asserts the straggler attribution NAMES
    rank 2 (max arrival lag strictly dominant + last-arriver counter
    advanced), the skew histogram observed every gather, and the
    clock-sync table closed at least one NTP loop. The wrapper
    additionally validates the merged catapult file."""
    import time

    from horovod_tpu.common import basics as _b
    from horovod_tpu.common import trace as _htrace

    ssum = float(sum(range(1, size + 1)))
    x = np.full(256, float(rank + 1), np.float64)
    for _ in range(60):
        out = hvd.allreduce(x, average=False, name="tw.g")
        np.testing.assert_allclose(np.asarray(out)[:1], ssum)
        time.sleep(0.02)
    # let one more publish interval pass so tail spans/echoes ship
    time.sleep(0.7)
    hvd.barrier(name="tw.flush")
    if rank == 0:
        rt = _b.runtime()
        st = rt._straggler
        assert st is not None
        line = st.report_line()
        assert line, "straggler window empty after 60 gathers"
        local = hvd.metrics()["local"]

        def metric(name, field="v", default=0.0):
            return local.get(name, {}).get(field, default)

        lag2 = metric('hvd_arrival_lag_seconds{peer="2"}')
        # the injected 250ms delay shows in rank 2's worst lag...
        assert lag2 >= 0.15, (lag2, local)
        # ...but a loaded host can hand a healthy rank ONE comparable
        # scheduling stall, so the attribution signal is the
        # last-arriver COUNTER (sustained, 10 repeated delays), which
        # must name rank 2 over every healthy peer — and the report
        # line is that attribution
        c2 = metric('hvd_last_arriver_total{peer="2"}')
        assert c2 >= 10, local
        for r in range(1, size):
            if r != 2:
                assert c2 > metric(
                    f'hvd_last_arriver_total{{peer="{r}"}}'), \
                    (r, local)
        assert "rank 2 last-arriver" in line, line
        skew = local.get("hvd_cycle_skew_seconds", {})
        assert skew.get("count", 0) >= 30, skew
        # build identity rides the same registry
        assert any(n.startswith("hvd_build_info{") for n in local), \
            sorted(local)[:20]
        # the piggybacked NTP exchange closed: offsets exist and are
        # sane for same-host processes
        offs = _htrace.clock().offsets()
        assert offs, "no clock-sync echo ever closed"
        for r, (off, rtt) in offs.items():
            assert abs(off) < 1.0 and 0.0 <= rtt < 1.0, (r, off, rtt)
    hvd.barrier(name="tw.done")


def scenario_trace_native_arrivals(hvd, rank, size):
    """Arrival stamps must cover the native steady gather
    (hvd_steady_coord): metrics armed, socket star + speculation +
    zero-copy on — the steady loop collapses into one-call native
    cycles, and the coordinator's skew histogram must keep observing
    every gather while they run."""
    from horovod_tpu import native as _nat
    from horovod_tpu.common import basics as _b

    ssum = float(sum(range(1, size + 1)))
    x = np.full(1024, float(rank + 1), np.float32)
    for _ in range(40):
        out = hvd.allreduce(x, average=False, name="tn.g")
    np.testing.assert_allclose(np.asarray(out)[:1], ssum)
    hvd.barrier(name="tn.flush")
    rt = _b.runtime()
    if rank == 0:
        stats = rt.negotiation_cache_stats()
        local = hvd.metrics()["local"]
        skew = local.get("hvd_cycle_skew_seconds", {})
        assert skew.get("count", 0) > 0, (skew, stats)
        if _nat.get() is not None:
            # the C loop carried the world — and the skew histogram
            # kept advancing through it (hvd_steady_coord stamps)
            assert stats["native_steady_cycles"] >= 5, stats
            assert skew["count"] >= stats["native_steady_cycles"], \
                (skew, stats)
        # exactly one last-arriver is charged per stamped gather
        last_total = sum(
            rec.get("v", 0) for name, rec in local.items()
            if name.startswith("hvd_last_arriver_total"))
        assert last_total == skew["count"], (last_total, skew)
    hvd.barrier(name="tn.done")


def scenario_flight_sigkill(hvd, rank, size):
    """SIGKILL mid-steady-cycle (fault spec + flight dir set by the
    wrapper): every survivor must (a) raise WorldAbortedError naming
    the dead rank — the PR 2 invariant — and (b) find its OWN
    flight-recorder postmortem dump on disk, written by the abort
    path with no profiling armed, naming the dead rank and containing
    the final cycles."""
    import json as _json
    import time

    from horovod_tpu.common.status import WorldAbortedError

    victim = 2
    deadline_s = float(os.environ["HOROVOD_HEARTBEAT_TIMEOUT"]) + 12.0
    x = np.full(512, float(rank + 1), np.float32)
    t0 = time.monotonic()
    aborted = None
    while True:
        try:
            hvd.allreduce(x, average=False, name="fs.g")
        except WorldAbortedError as e:
            aborted = e
            break
        assert time.monotonic() - t0 < deadline_s, (
            f"rank {rank}: collectives kept succeeding {deadline_s}s "
            f"after the fault")
    assert aborted.origin_rank == victim, (rank, str(aborted))
    assert f"rank {victim}" in str(aborted), str(aborted)
    # The abort handler dumps on the background thread; the user
    # thread may observe the error first — wait briefly.
    path = os.path.join(os.environ["HOROVOD_TPU_FLIGHT_DIR"],
                        f"hvd-flight-rank{rank}.pid{os.getpid()}"
                        f".jsonl")
    deadline = time.monotonic() + 15.0
    lines = []
    while time.monotonic() < deadline:
        try:
            lines = [_json.loads(line) for line in open(path)]
        except (OSError, ValueError):
            lines = []  # not there yet, or caught mid-write
        # the header's "events" count says when the block is complete
        if lines and len(lines) >= 1 + lines[0].get("events", 0):
            break
        time.sleep(0.05)
    assert lines and len(lines) >= 1 + lines[0].get("events", 0), \
        f"no complete flight dump at {path}"
    header, events = lines[0], lines[1:]
    assert header["flight"] == 1 and header["rank"] == rank
    assert header["origin"] == victim, header
    assert f"rank {victim}" in header["cause"], header
    assert set(header["build"]) == {"version", "native", "knobs",
                                    "flags"}
    cycles = [e["cycle"] for e in events if e["ev"] == "cycle"]
    assert cycles and max(cycles) >= 10, (
        "dump does not contain the final cycles", cycles[-5:])
    assert any(e["ev"] == "abort" and e.get("arg") == victim
               for e in events), events[-5:]
    hvd.shutdown()


def scenario_kitchen_sink(hvd, rank, size):
    """Every auxiliary subsystem enabled at once — autotune (+log),
    timeline (+cycle marks), hierarchical shm over a fake 2-host
    topology, stall checker armed — under mixed per-rank-shuffled
    traffic with a mid-stream coordinator ERROR and recovery. The
    artifacts (timeline JSON, autotune CSV) are verified by the
    spawning test after shutdown."""
    from horovod_tpu.common import basics as _b
    from horovod_tpu.common.status import HorovodInternalError

    rt = _b.runtime()
    assert rt.parameter_manager is not None, "autotune must be active"
    assert rt.timeline.enabled or rank != 0

    ssum = sum(range(1, size + 1))
    rng = np.random.RandomState(77 + rank)  # per-rank order!
    for round_ in range(20):
        jobs = [("ar", i) for i in range(4)] + \
               [("bc", i) for i in range(4)] + \
               [("ag", i) for i in range(2)] + \
               [("rs", i) for i in range(2)]
        handles = {}
        for idx in rng.permutation(len(jobs)):
            kind, i = jobs[idx]
            tag = f"ks{round_}.{kind}{i}"
            if kind == "ar":
                handles[(kind, i)] = hvd.allreduce_async(
                    np.full(300 + i, float(rank + 1) * (i + 1),
                            np.float64), average=False, name=tag)
            elif kind == "bc":
                handles[(kind, i)] = hvd.broadcast_async(
                    np.full(16, float(rank * 10 + i), np.float32),
                    root_rank=i % size, name=tag)
            elif kind == "ag":
                handles[(kind, i)] = hvd.allgather_async(
                    np.full((rank + 1, 3), float(rank + i), np.float32),
                    name=tag)
            else:
                handles[(kind, i)] = hvd.reducescatter_async(
                    np.arange(size * 4, dtype=np.float64) + rank,
                    name=tag)
        for (kind, i), h in handles.items():
            out = np.asarray(hvd.synchronize(h))
            if kind == "ar":
                np.testing.assert_allclose(
                    out, np.full(300 + i, ssum * (i + 1)))
            elif kind == "bc":
                np.testing.assert_allclose(
                    out, float((i % size) * 10 + i))
            elif kind == "ag":
                assert out.shape == (sum(r + 1 for r in range(size)), 3)
            else:
                base = size * np.arange(size * 4) + sum(range(size))
                np.testing.assert_allclose(
                    out, base[rank * 4:(rank + 1) * 4])

        if round_ == 3:
            # coordinator ERROR mid-storm: mismatched shapes...
            shape = (4, 5) if rank == 0 else (4, 6)
            try:
                hvd.allreduce(np.ones(shape, np.float32), name="ks.bad")
            except HorovodInternalError:
                pass
            else:
                raise AssertionError("expected HorovodInternalError")
            # ...and the world keeps negotiating afterwards
            np.testing.assert_allclose(
                hvd.allreduce(np.ones(5, np.float32), average=False,
                              name="ks.recover"),
                size * np.ones(5))

    # Pump the autotuner to its first LOGGED sample. The discrete
    # (algorithm x wire) sweep consumes a topology-dependent number of
    # busy cycles before the Bayesian phase appends CSV row 1, and
    # cycle coalescing makes "N rounds" a nondeterministic cycle
    # count — so drive small allreduces until the coordinator's log
    # shows a data row, agreeing on the verdict through the reduction
    # itself (every rank must leave the loop on the same cycle).
    log_path = os.environ.get("HOROVOD_AUTOTUNE_LOG", "")
    for pump in range(600):
        hvd.allreduce(np.full(64, 1.0, np.float64), average=False,
                      name=f"ks.pump{pump}")
        done = 0.0
        if rank == 0 and log_path:
            try:
                with open(log_path) as f:
                    rows = [ln for ln in f.read().splitlines()
                            if ln.strip()]
                done = float(len(rows) >= 2)
            except OSError:
                done = 0.0
        agreed = np.asarray(hvd.allreduce(
            np.full(1, done, np.float64), average=False,
            name=f"ks.pumpchk{pump}"))
        if agreed[0] > 0:
            break
    else:
        raise AssertionError("autotune never logged a sample row")

    hvd.barrier(name="ks.done")


def scenario_bf16_host_path(hvd, rank, size):
    """bfloat16 — the TPU-native wire/accumulate dtype — through the
    host collectives (native sum kernel or numpy/ml_dtypes fallback)."""
    try:
        import ml_dtypes
    except ImportError:
        return  # numpy-only install: nothing to test
    # careful: bf16 * python-int silently promotes to f32 (ml_dtypes
    # weak promotion) — cast LAST so the wire dtype really is bf16
    x = np.full(64, float(rank + 1)).astype(ml_dtypes.bfloat16)
    out = hvd.allreduce(x, average=False, name="bf.ar")
    assert np.asarray(out).dtype == ml_dtypes.bfloat16, \
        np.asarray(out).dtype
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               sum(range(1, size + 1)))
    g = hvd.allgather(
        np.full((2, 3), float(rank)).astype(ml_dtypes.bfloat16),
        name="bf.ag")
    assert np.asarray(g).shape == (2 * size, 3)
    assert np.asarray(g).dtype == ml_dtypes.bfloat16
    b = hvd.broadcast(np.full(4, float(rank)).astype(ml_dtypes.bfloat16),
                      root_rank=1, name="bf.bc")
    np.testing.assert_allclose(np.asarray(b, np.float32), 1.0)


def _metric_value(hvd, name: str) -> float:
    rec = hvd.metrics()["local"].get(name)
    if rec is None:
        return 0.0
    return rec["v"] if "v" in rec else rec.get("count", 0)


def scenario_compression_steady_zero_copy(hvd, rank, size):
    """Compressed steady state end to end (HOROVOD_COMPRESSION=bf16 +
    metrics armed + shm/ring off by the pytest wrapper): a steady
    grouped-allreduce loop of bf16-exact values must (a) stay exact,
    (b) keep riding the fused speculative round (and the native
    zero-copy cycle when the library is loaded) with the COMPRESSED
    payload — hvd_data_copies_total delta stays 0, proving the
    ISSUE 9 contract that compression composes with the PR 6 plane —
    and (c) report wire bytes actually saved."""
    from horovod_tpu.common import basics as _b
    from horovod_tpu import native as _nat

    ssum = sum(range(1, size + 1))
    # Small integers: exactly representable in bf16, so the compressed
    # world is assertable bit-for-bit.
    xs = [np.full(256 + i, float(rank + 1) * (i + 1), np.float32)
          for i in range(6)]

    def step():
        hs = hvd.grouped_allreduce_async(xs, average=False, name="cz")
        for i, h in enumerate(hs):
            np.testing.assert_allclose(np.asarray(hvd.synchronize(h)),
                                       ssum * (i + 1.0))

    for _ in range(5):
        step()
    hvd.barrier(name="cz.bar")
    rt = _b.runtime()
    s0 = rt.negotiation_cache_stats()
    copies0 = _metric_value(hvd, "hvd_data_copies_total")
    saved0 = _metric_value(hvd, "hvd_wire_bytes_saved_total")
    for _ in range(25):
        step()
    s1 = rt.negotiation_cache_stats()
    copies1 = _metric_value(hvd, "hvd_data_copies_total")
    saved1 = _metric_value(hvd, "hvd_wire_bytes_saved_total")
    assert s1["spec_cycles"] > s0["spec_cycles"], (rank, s0, s1)
    if _nat.get() is not None:
        assert s1["native_steady_cycles"] > s0["native_steady_cycles"], \
            (rank, s0, s1)
    assert copies1 - copies0 == 0, (rank, copies0, copies1)
    assert saved1 > saved0, (rank, saved0, saved1)
    # bf16 halves the payload: per fused step the saving is half the
    # uncompressed fused bytes
    per_step = sum(x.nbytes for x in xs) // 2
    assert saved1 - saved0 >= 20 * per_step, (rank, saved0, saved1)


def scenario_compression_hetero(hvd, rank, size):
    """Heterogeneous compression knobs (the pytest wrapper proposes
    bf16 on ONE rank only, or on all — same scenario both ways): the
    coordinator resolves every batch to the common denominator, and a
    world whose verdict is `none` must be BIT-EXACT with a fresh
    all-none world replaying the same submissions — the wrapper runs
    both worlds and compares the files byte-for-byte."""
    rng = np.random.RandomState(1000 + rank)
    outs = []
    for step in range(8):
        x = rng.randn(777).astype(np.float32)
        outs.append(np.asarray(
            hvd.allreduce(x, average=False, name=f"hx.{step}")))
    g = hvd.allgather(np.asarray([[float(rank)]], np.float32),
                      name="hx.ag")
    outs.append(np.asarray(g))
    out_path = os.environ.get("HVD_COMPRESSION_OUT")
    if rank == 0 and out_path:
        np.save(out_path, np.concatenate([o.reshape(-1) for o in outs]))
    # a bf16-proposing rank in a mixed world must see an uncompressed
    # verdict: zero wire bytes saved anywhere
    if os.environ.get("HOROVOD_TPU_METRICS") == "1":
        assert _metric_value(hvd, "hvd_wire_bytes_saved_total") == 0, \
            rank


def scenario_twolevel_allreduce(hvd, rank, size):
    """Two-level hierarchical allreduce on a (fake) multi-host world
    (HOROVOD_TWO_LEVEL=1 + HOROVOD_COMPRESSION=bf16 + metrics armed by
    the wrapper): intra-host shm reduce, cross-host ring among local
    roots, intra-host shm broadcast. Values are bf16-exact so the
    compressed cross leg is assertable exactly; the per-algorithm op
    counter proves the plane actually carried the batches."""
    ssum = sum(range(1, size + 1))
    for step in range(6):
        x = np.full(2048, float(rank + 1), np.float32)
        out = hvd.allreduce(x, average=False, name=f"tl.{step}")
        np.testing.assert_allclose(np.asarray(out), ssum)
    # a bandwidth-bound op through the same plane
    big = np.full(1 << 18, float(rank + 1), np.float32)
    out = hvd.allreduce(big, average=False, name="tl.big")
    np.testing.assert_allclose(np.asarray(out), ssum)
    # non-allreduce collectives keep their own planes alongside
    g = hvd.allgather(np.full((2, 2), float(rank), np.float32),
                      name="tl.ag")
    assert np.asarray(g).shape == (2 * size, 2)
    assert _metric_value(hvd, "hvd_ops_twolevel_total") >= 7, rank
    # Only LOCAL ROOTS put bytes on the cross-host allreduce leg —
    # a leaf's two-level legs (RAM) are deliberately not compressed,
    # so its counter holds EXACTLY the allgather's saving (tl.ag
    # ships a 16-byte f32 block at bf16 wire = 8 bytes saved;
    # allgather wire compression engages on every rank — it rides
    # the socket plane, which has no RAM leg).
    saved = _metric_value(hvd, "hvd_wire_bytes_saved_total")
    ag_saved = (2 * 2 * 4) // 2
    if hvd.local_rank() == 0:
        assert saved > ag_saved, rank
    else:
        assert saved == ag_saved, (rank, saved)


def scenario_compression_train_parity(hvd, rank, size):
    """Convergence-parity leg (ISSUE 9): train the toy TransformerLM
    from models/ data-parallel for a fixed schedule, gradients
    allreduced at this world's HOROVOD_COMPRESSION; rank 0 writes the
    loss trajectory for the pytest wrapper to compare across wire
    dtypes (none vs bf16 vs int8+error-feedback)."""
    import jax
    import jax.numpy as jnp
    from horovod_tpu.models.transformer import (
        TransformerConfig, TransformerLM, lm_loss,
    )

    cfg = TransformerConfig(vocab_size=64, num_layers=2, num_heads=2,
                            head_dim=8, max_seq_len=16,
                            dtype=jnp.float32)
    model = TransformerLM(cfg)
    data_rng = np.random.RandomState(4242 + rank)  # per-rank shards
    # FIXED batch per rank (memorization task): loss must fall
    # monotonically-ish within the short schedule, giving the parity
    # comparison a real training signal instead of noise-floor drift.
    tokens = jnp.asarray(data_rng.randint(0, 64, (4, 16)), jnp.int32)
    params = model.init(jax.random.key(0), tokens)  # identical ranks

    @jax.jit
    def loss_grads(p, t):
        def f(p):
            return lm_loss(model.apply(p, t), t)
        return jax.value_and_grad(f)(p)

    lr = 0.1
    losses = []
    for step in range(10):
        t = tokens
        loss, g = loss_grads(params, t)
        flat = [np.asarray(x, np.float32)
                for x in jax.tree_util.tree_leaves(g)]
        # SAME group name every step: the steady-state fast path (and
        # with it the compressed spec cycle) engages mid-run
        outs = hvd.grouped_allreduce(flat, average=True, name="gp")
        new_flat = [p - lr * jnp.asarray(gavg)
                    for p, gavg in zip(jax.tree_util.tree_leaves(params),
                                       outs)]
        params = jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(params), new_flat)
        losses.append(float(loss))
    assert all(np.isfinite(losses)), (rank, losses)
    # world-averaged final loss so every rank contributes to the
    # parity number the wrapper compares
    final = np.asarray(hvd.allreduce(
        np.asarray([losses[-1]], np.float64), average=True,
        name="gp.final"))
    out_path = os.environ.get("HVD_COMPRESSION_OUT")
    if rank == 0 and out_path:
        import json
        with open(out_path, "w") as f:
            json.dump({"final_loss": float(final[0]),
                       "losses": losses}, f)


def scenario_rank_death(hvd, rank, size):
    """A rank dying abruptly mid-job must surface on the survivors as
    a clean shutdown error on the next collective — never a hang
    (reference analog: shutdown fan-out + SHUT_DOWN_ERROR callbacks,
    operations.cc:898-913; under mpirun the dead orted kills the world,
    here the library itself detects the dead control channel)."""
    import time
    from horovod_tpu.common.status import HorovodInternalError
    x = np.full(50, float(rank + 1), np.float32)
    out = hvd.allreduce(x, average=False, name="rd.ok")
    np.testing.assert_allclose(out, sum(range(1, size + 1)))
    if rank == 1:
        os._exit(0)  # abrupt death; 0 so the harness reads it as clean
    time.sleep(0.5)
    try:
        hvd.allreduce(x, average=False, name="rd.after")
        raise AssertionError("collective after a rank death must fail")
    except HorovodInternalError:
        pass
    # shutdown after the world collapsed stays idempotent
    hvd.shutdown()


def scenario_rank_death_hier(hvd, rank, size):
    """A REMOTE LEAF dying under the hierarchical control plane: its
    local root's relay recv fails, the root's background loop tears
    down, the coordinator sees that host's channel die, and every
    survivor errors out cleanly on its next collective — no hang at
    any tier of the hierarchy."""
    import time
    from horovod_tpu.common import basics as _b
    from horovod_tpu.common.status import HorovodInternalError

    topo = _b.runtime().controller.topology
    assert topo.cross_size > 1, "scenario expects a multihost topology"
    x = np.full(50, float(rank + 1), np.float32)
    out = hvd.allreduce(x, average=False, name="rdh.ok")
    np.testing.assert_allclose(out, sum(range(1, size + 1)))
    if rank == size - 1:  # the last host's leaf (migrated behind root)
        os._exit(0)
    time.sleep(0.5)
    try:
        hvd.allreduce(x, average=False, name="rdh.after")
        raise AssertionError("collective after a leaf death must fail")
    except HorovodInternalError:
        pass
    hvd.shutdown()


def scenario_coordinator_death(hvd, rank, size):
    """The COORDINATOR (rank 0, which also hosts the controller socket)
    dying abruptly is the worst failure: every worker's control channel
    drops at once. Workers must fail loudly on their next collective and
    shut down cleanly — never hang (complements scenario_rank_death,
    which kills a non-coordinator)."""
    import time
    from horovod_tpu.common.status import HorovodInternalError
    x = np.full(16, float(rank + 1), np.float32)
    out = hvd.allreduce(x, average=False, name="cd.ok")
    np.testing.assert_allclose(out, sum(range(1, size + 1)))
    if rank == 0:
        os._exit(0)  # coordinator vanishes, controller socket with it
    time.sleep(0.5)
    try:
        hvd.allreduce(x, average=False, name="cd.after")
        raise AssertionError(
            "collective after coordinator death must fail")
    except HorovodInternalError:
        pass
    hvd.shutdown()


def _await_world_abort(hvd, rank, expect_origin, deadline_s, name):
    """Drive allreduces until the fail-fast protocol surfaces
    :class:`WorldAbortedError`; assert it names the failed rank and
    lands within the detection deadline, then prove that a
    subsequently-enqueued handle fails the same structured way.

    No external watchdog does the unblocking here: if the in-band
    heartbeat/abort machinery regresses, the blocked collective trips
    the harness alarm guard and the test fails with thread stacks."""
    import time
    from horovod_tpu.common.status import WorldAbortedError

    t0 = time.monotonic()
    i = 0
    while True:
        try:
            hvd.allreduce(np.ones(64, np.float32), average=False,
                          name=f"{name}/{i}")
        except WorldAbortedError as e:
            elapsed = time.monotonic() - t0
            assert e.origin_rank == expect_origin, (
                f"rank {rank}: abort blamed rank {e.origin_rank}, "
                f"expected {expect_origin}: {e}")
            assert f"rank {expect_origin}" in str(e), str(e)
            assert elapsed < deadline_s, (
                f"rank {rank}: detection took {elapsed:.1f}s "
                f"(deadline {deadline_s}s)")
            break
        i += 1
        assert time.monotonic() - t0 < deadline_s, (
            f"rank {rank}: collectives kept succeeding for "
            f"{deadline_s}s after the fault")
    # handles enqueued AFTER the world died must fail structurally
    # too — never hang, never a bare UnknownError
    try:
        hvd.allreduce(np.ones(4, np.float32), average=False,
                      name=f"{name}/post")
        raise AssertionError("enqueue after world abort must fail")
    except WorldAbortedError as e:
        assert e.origin_rank == expect_origin, str(e)
    hvd.shutdown()  # stays idempotent after the world collapsed


def scenario_abort_sigkill_leaf(hvd, rank, size):
    """SIGKILL a non-coordinator rank squarely mid-allreduce (fault
    injection lands it just before that rank executes its 3rd
    negotiated response, while every peer is already inside the same
    collective): all survivors — including the coordinator — must
    raise WorldAbortedError naming the dead rank within the
    detection deadline. HOROVOD_FAULT_SPEC is set by the pytest
    wrapper (tests/test_multiprocess.py)."""
    victim = 1
    deadline = float(os.environ["HOROVOD_HEARTBEAT_TIMEOUT"]) + 12.0
    _await_world_abort(hvd, rank, victim, deadline, "sk.leaf")


def scenario_abort_sigkill_local_root(hvd, rank, size):
    """SIGKILL a LOCAL ROOT of the hierarchical control tier
    mid-collective: its leaves lose their upward relay, the
    coordinator loses that host's aggregate channel, and the abort
    must reach every survivor at every tier of the tree."""
    from horovod_tpu.common import basics as _b
    topo = _b.runtime().controller.topology
    assert topo.cross_size > 1, "scenario expects a multihost topology"
    victim = size // 2  # first rank of the second fake host = its root
    assert topo.local_roots[1] == victim
    deadline = float(os.environ["HOROVOD_HEARTBEAT_TIMEOUT"]) + 12.0
    _await_world_abort(hvd, rank, victim, deadline, "sk.root")


def scenario_abort_sigkill_coordinator(hvd, rank, size):
    """SIGKILL the coordinator (rank 0) mid-collective — the worst
    case: every worker's control channel dies at once, and there is no
    coordinator left to fan the ABORT. Workers must each detect the
    dead upward channel themselves and fail with WorldAbortedError
    naming rank 0."""
    deadline = float(os.environ["HOROVOD_HEARTBEAT_TIMEOUT"]) + 12.0
    _await_world_abort(hvd, rank, 0, deadline, "sk.coord")


def scenario_abort_heartbeat_hang(hvd, rank, size):
    """A rank that goes SILENT without dying (SIGSTOP-like wedge, host
    network loss: the kernel never sends FIN/RST, so TCP errors never
    fire) is detectable ONLY by the heartbeat recv deadline. Fault
    injection wedges rank 1's background loop; survivors must abort
    within HOROVOD_HEARTBEAT_TIMEOUT + slack, naming rank 1."""
    import time
    from horovod_tpu.common.status import HorovodInternalError

    victim = 1
    hb_timeout = float(os.environ["HOROVOD_HEARTBEAT_TIMEOUT"])
    if rank == victim:
        # the wedged rank unblocks when its hang elapses, then finds
        # the world gone — any structured internal error is acceptable
        # on the faulty rank itself (it may blame the coordinator,
        # whose channel it finds dead on wake-up)
        try:
            while True:
                hvd.allreduce(np.ones(64, np.float32), average=False,
                              name="hb.hang")
        except HorovodInternalError:
            pass
        hvd.shutdown()
        return
    t0 = time.monotonic()
    _await_world_abort(hvd, rank, victim, hb_timeout + 15.0, "hb.hang")
    # the point of the heartbeat: detection is BOUNDED by the knob,
    # not by the 8 s wedge ending or TCP keepalive (hours)
    assert time.monotonic() - t0 < hb_timeout + 15.0


def scenario_abort_sigkill_ring(hvd, rank, size):
    """SIGKILL a rank while the RING data plane is active (threshold
    lowered so these payloads ride the 2-phase ring): the survivor
    whose ring link dies must blame the dead NEIGHBOR, not itself,
    and the abort must fan to everyone."""
    victim = 1
    deadline = float(os.environ["HOROVOD_HEARTBEAT_TIMEOUT"]) + 12.0
    import time
    from horovod_tpu.common.status import WorldAbortedError

    t0 = time.monotonic()
    i = 0
    while True:
        try:
            # over HOROVOD_TPU_RING_THRESHOLD (1024) -> ring path
            hvd.allreduce(np.ones(50_000, np.float64), average=False,
                          name=f"rk/{i}")
        except WorldAbortedError as e:
            assert e.origin_rank == victim, (rank, e.origin_rank, str(e))
            assert time.monotonic() - t0 < deadline
            break
        i += 1
        assert time.monotonic() - t0 < deadline
    hvd.shutdown()


def scenario_abort_severed_link(hvd, rank, size):
    """Fault-injected link severance (abrupt close of rank 1's upward
    control channel, process still alive): both sides of the cut must
    converge on a world abort — the coordinator names the peer whose
    channel died; the severed rank finds its own channel closed."""
    from horovod_tpu.common.status import HorovodInternalError

    victim = 1
    deadline = float(os.environ["HOROVOD_HEARTBEAT_TIMEOUT"]) + 12.0
    if rank == victim:
        # After severing its own upward channel, this rank's next
        # control exchange fails; it blames its upward peer (rank 0)
        # since a cut wire is indistinguishable from a dead peer.
        try:
            while True:
                hvd.allreduce(np.ones(64, np.float32), average=False,
                              name="sever")
        except HorovodInternalError:
            pass
        hvd.shutdown()
        return
    _await_world_abort(hvd, rank, victim, deadline, "sever")


def scenario_subset_world(hvd, rank, size):
    """hvd.init(comm=[1, 2]) on a 3-process launch: ranks 1 and 2 form
    a 2-rank sub-world (renumbered 0 and 1, rank 1 hosting the
    coordinator) and allreduce; rank 0 is not a member, comes up as a
    size-1 world, and keeps working locally (reference:
    common/__init__.py:58-84 init(comm=ranks))."""
    assert size == 3, "scenario expects 3 launched processes"
    hvd.init(comm=[1, 2])
    if rank == 0:
        # the abstaining process: local world, local collectives work
        assert hvd.size() == 1 and hvd.rank() == 0
        out = hvd.allreduce(np.full(4, 7.0, np.float32),
                            average=False, name="solo.ar")
        np.testing.assert_allclose(out, 7.0)
    else:
        assert hvd.size() == 2
        assert hvd.rank() == rank - 1  # renumbered in list order
        x = np.full(5, float(rank), np.float32)  # global ranks 1, 2
        out = hvd.allreduce(x, average=False, name="sub.ar")
        np.testing.assert_allclose(out, 3.0)  # 1 + 2, never rank 0's 7
        b = hvd.broadcast(np.full(2, float(rank), np.float64),
                          root_rank=1, name="sub.bc")
        # sub-world root 1 == global rank 2
        np.testing.assert_allclose(b, 2.0)


scenario_subset_world.no_auto_init = True


def scenario_subset_world_hier(hvd, rank, size):
    """init(comm=[2..5]) on a 6-process launch with fake hosts
    rank//2: the sub-world spans two multi-rank hosts, so the
    HIERARCHICAL control plane activates INSIDE the subset — the
    sub-coordinator (global rank 2, renumbered 0) keeps one local leaf
    channel plus one aggregate channel for the remote host, and every
    collective stays exact; abstaining ranks keep local worlds."""
    assert size == 6, "scenario expects 6 launched processes"
    hvd.init(comm=[2, 3, 4, 5])
    from horovod_tpu.common import basics as _b

    if rank < 2:
        assert hvd.size() == 1
        out = hvd.allreduce(np.full(3, 5.0, np.float32),
                            average=False, name="solo.ar")
        np.testing.assert_allclose(out, 5.0)
        return
    assert hvd.size() == 4 and hvd.rank() == rank - 2
    ctl = _b.runtime().controller
    assert ctl.topology.cross_size == 2, ctl.topology.cross_size
    if hvd.rank() == 0:
        # 1 local leaf + 1 remote aggregate root
        assert len(ctl._channels) == 2, len(ctl._channels)
        assert ctl._has_aggregates

    x = np.full(5, float(rank), np.float32)  # global ranks 2..5
    out = hvd.allreduce(x, average=False, name="subh.ar")
    np.testing.assert_allclose(out, 14.0)  # 2+3+4+5, never ranks 0/1
    for root in range(4):
        b = hvd.broadcast(np.full(2, float(rank), np.float64),
                          root_rank=root, name=f"subh.bc{root}")
        np.testing.assert_allclose(b, float(root + 2))
    g = hvd.allgather(np.full((hvd.rank() + 1, 2), float(rank),
                              np.float32), name="subh.ag")
    off = 0
    for r in range(4):
        np.testing.assert_allclose(
            np.asarray(g)[off:off + r + 1], float(r + 2))
        off += r + 1


scenario_subset_world_hier.no_auto_init = True


def scenario_mxnet(hvd, rank, size):
    """Execute the whole MXNet adapter surface under a real 2-process
    world via the NDArray-protocol double (tests/fake_mxnet.py):
    collectives, in-place variants, parameter broadcast with deferred
    init, DistributedOptimizer (scalar + aggregated-list update), and
    DistributedTrainer._allreduce_grads (reference:
    horovod/mxnet/__init__.py:38-140)."""
    from tests import fake_mxnet
    fake_mxnet.install()
    import horovod_tpu.mxnet as hmx
    nd = fake_mxnet

    ssum = sum(range(1, size + 1))
    x = nd.NDArray(np.full(4, float(rank + 1), np.float32))
    out = hmx.allreduce(x, average=False, name="mx.ar")
    assert isinstance(out, nd.NDArray)
    np.testing.assert_allclose(out.asnumpy(), ssum)
    assert out.dtype == np.float32

    hmx.allreduce_(x, average=True, name="mx.ar_")
    np.testing.assert_allclose(x.asnumpy(), ssum / size)

    g = hmx.allgather(
        nd.NDArray(np.full((rank + 1, 2), float(rank), np.float32)),
        name="mx.ag")
    assert g.asnumpy().shape == (sum(r + 1 for r in range(size)), 2)

    b = hmx.broadcast(nd.NDArray(np.full(3, float(rank), np.float64)),
                      root_rank=1, name="mx.bc")
    np.testing.assert_allclose(b.asnumpy(), 1.0)

    # parameter broadcast with one deferred-init parameter: skipped on
    # the first pass, carried on the second after initialize()
    params = {
        "w": nd.Parameter("w", np.full(4, float(rank * 10 + 1))),
        "late": nd.Parameter("late", np.full(2, float(rank * 10 + 2)),
                             deferred=True),
    }
    hmx.broadcast_parameters(params, root_rank=0)
    np.testing.assert_allclose(params["w"].data().asnumpy(), 1.0)
    params["late"].initialize()
    hmx.broadcast_parameters(params, root_rank=0)
    np.testing.assert_allclose(params["late"].data().asnumpy(), 2.0)

    # DistributedOptimizer: scalar-index and aggregated-list updates
    class RecordingOpt:
        def __init__(self):
            self.calls = []

        def update(self, index, weight, grad, state):
            self.calls.append((index, grad))

        def update_multi_precision(self, index, weight, grad, state):
            self.calls.append(("mp", index, grad))

    opt = hmx.DistributedOptimizer(RecordingOpt())
    grad = nd.NDArray(np.full(3, float(rank + 1), np.float32))
    opt.update(7, None, grad, None)
    np.testing.assert_allclose(grad.asnumpy(), ssum / size)
    grads = [nd.NDArray(np.full(2, float(rank + 1) * (i + 1),
                                np.float32)) for i in range(2)]
    opt.update_multi_precision([1, 2], None, grads, None)
    for i, gr in enumerate(grads):
        np.testing.assert_allclose(gr.asnumpy(),
                                   ssum * (i + 1) / size)
    assert len(opt._opt.calls) == 2

    # DistributedTrainer: _allreduce_grads sums, _scale divides by size
    ps = [nd.Parameter(f"p{i}", np.ones(3),
                       grad=np.full(3, float(rank + 1) * (i + 1)))
          for i in range(2)]
    ps.append(nd.Parameter("frozen", np.ones(2),
                           grad=np.full(2, 99.0), grad_req="null"))
    trainer = hmx.DistributedTrainer(ps, RecordingOpt())
    assert trainer._scale == 1.0 / size
    trainer._allreduce_grads()
    for i in range(2):
        np.testing.assert_allclose(ps[i].list_grad()[0].asnumpy(),
                                   ssum * (i + 1))
    np.testing.assert_allclose(ps[2].list_grad()[0].asnumpy(), 99.0)

    # unwrap guard: a wrapped optimizer must not double-reduce
    t2 = hmx.DistributedTrainer(ps[:1], opt)
    assert not isinstance(t2._optimizer, hmx.DistributedOptimizer)


def scenario_autotune(hvd, rank, size):
    """End-to-end autotune under a real 2-process world: drive traffic
    until the coordinator's Bayesian tuner converges, then verify every
    worker adopted the coordinator's tuned values via the ResponseList
    trailer (reference: SyncParams, parameter_manager.cc:64-78)."""
    import time as _t
    from horovod_tpu.common import basics as _b
    rt = _b.runtime()
    pm = rt.parameter_manager
    assert pm is not None, "HOROVOD_AUTOTUNE=1 must create the manager"

    x = np.full(4096, float(rank + 1), np.float32)
    converged = False
    for i in range(2000):
        hvd.allreduce(x, average=False, name=f"at.{i}")
        # world-consistent loop exit: rank 0 broadcasts its tuning state
        flag = 0.0 if rank != 0 else (0.0 if pm.tuning else 1.0)
        done = hvd.broadcast(np.asarray([flag]), root_rank=0,
                             name=f"at.done/{i}")
        if float(done[0]) == 1.0:
            converged = True
            break
    assert converged, "autotune did not converge within the op budget"

    # one extra collective so the cycle that carried the converged
    # trailer has definitely passed through apply_synced on workers
    hvd.barrier(name="at.sync")
    _t.sleep(0.2)

    tuned = hvd.broadcast(np.asarray(pm._current, np.float64),
                          root_rank=0, name="at.vals")
    if rank != 0:
        # rtol bounded by the wire trailer's float32 round-trip
        np.testing.assert_allclose(np.asarray(pm._current, np.float64),
                                   tuned, rtol=1e-5)
        assert abs(pm.fusion_threshold_bytes()
                   - tuned[0] * 1024 * 1024) <= 1
        assert abs(pm.cycle_time_ms() - tuned[1]) < 1e-4


def scenario_shm_hier_allreduce(hvd, rank, size):
    """Multi-host (fake-host) world: allreduce rides the hierarchical
    shm path — local shm reduce, cross exchange among local roots,
    local shm broadcast (reference: NCCLHierarchicalAllreduce,
    nccl_operations.cc:167-372) — while other collectives stay on the
    socket backend."""
    from horovod_tpu.common import basics as _b
    ssum = sum(range(1, size + 1))

    x = np.arange(50_000, dtype=np.float64) + rank
    out = hvd.allreduce(x, average=False, name="sh.ar")
    np.testing.assert_allclose(
        out, size * np.arange(50_000, dtype=np.float64)
        + sum(range(size)))

    rt = _b.runtime()
    shm = [b for b in rt.op_manager._backends if b.name == "shm"][0]
    if hvd.local_size() > 1:
        assert shm._map is not None, "hier shm segment not established"
    else:
        # a solo host shares memory with nobody: no segment
        assert shm._map is None
    assert shm._hier, "topology should be multi-host"

    # zero-element allreduce must not wedge the protocol
    z = hvd.allreduce(np.empty(0, np.float32), average=False,
                      name="sh.zero")
    assert np.asarray(z).size == 0

    # fused batch + average through the hierarchical path
    handles = [hvd.allreduce_async(
        np.full(3000, float(rank + 1) * (i + 1), np.float32),
        average=True, name=f"sh.f/{i}") for i in range(4)]
    for i, h in enumerate(handles):
        np.testing.assert_allclose(
            hvd.synchronize(h), ssum * (i + 1) / size, rtol=1e-6)

    # segment growth in hier mode
    big = np.full(400_000, float(rank + 1), np.float32)
    np.testing.assert_allclose(
        hvd.allreduce(big, average=False, name="sh.big"), ssum)

    # non-allreduce collectives still work (socket backend path)
    g = hvd.allgather(np.full((rank + 1, 2), float(rank), np.float32),
                      name="sh.ag")
    assert g.shape[0] == sum(r + 1 for r in range(size))
    b = hvd.broadcast(np.full(3, float(rank), np.float64), root_rank=1,
                      name="sh.bc")
    np.testing.assert_allclose(b, 1.0)


def scenario_timeline(hvd, rank, size):
    """Drive one of each collective so rank 0's timeline (enabled via
    HOROVOD_TIMELINE in the harness env) records the full vocabulary
    (reference: test/test_timeline.py:42-58), including the fusion
    memcpy sub-activities a fused batch emits on the host planes
    (reference: mpi_operations.cc:35-62)."""
    x = np.full(64, float(rank + 1), np.float32)
    out = hvd.allreduce(x, average=False, name="tl.ar")
    np.testing.assert_allclose(out, sum(range(1, size + 1)))
    g = hvd.allgather(np.full((rank + 1, 2), float(rank), np.float32),
                      name="tl.ag")
    assert g.shape[0] == sum(r + 1 for r in range(size))
    hvd.broadcast(x, root_rank=0, name="tl.bc")
    # grouped members are guaranteed one fused batch -> the pack/unpack
    # memcpy spans are emitted deterministically
    outs = hvd.grouped_allreduce(
        [np.full(16, float(rank + 1) * (i + 1), np.float32)
         for i in range(3)], average=False, name="tl.grp")
    for i, o in enumerate(outs):
        np.testing.assert_allclose(
            o, sum(range(1, size + 1)) * (i + 1.0))


def scenario_shm_fallback(hvd, rank, size):
    """Segment creation failing on one rank must degrade the whole
    world to the socket backend together (agree() vote)."""
    from horovod_tpu.common import basics as _b
    from horovod_tpu.ops import shm_ops as _shm

    if rank == 1:
        real_open = _shm.os.open

        def _fail(path, *a, **k):
            if isinstance(path, str) and path.startswith("/dev/shm/"):
                raise OSError("forced shm failure (test)")
            return real_open(path, *a, **k)
        _shm.os = type(_shm.os)("os_shim")
        _shm.os.__dict__.update(__import__("os").__dict__)
        _shm.os.open = _fail

    x = np.full(1000, float(rank + 1), np.float64)
    out = hvd.allreduce(x, average=False, name="sf.ar")
    np.testing.assert_allclose(out, sum(range(1, size + 1)))

    rt = _b.runtime()
    shm = [b for b in rt.op_manager._backends if b.name == "shm"][0]
    assert shm._dead, "shm backend should be dead after the failed vote"
    assert shm._map is None

    # follow-up ops stay correct on the socket path
    out = hvd.allreduce(x, average=False, name="sf.ar2")
    np.testing.assert_allclose(out, sum(range(1, size + 1)))


def scenario_shm_multihost_disabled(hvd, rank, size):
    from horovod_tpu.common import basics as _b
    x = np.full(100, float(rank + 1), np.float32)
    out = hvd.allreduce(x, average=False, name="mh.ar")
    np.testing.assert_allclose(out, sum(range(1, size + 1)))
    rt = _b.runtime()
    shm = [b for b in rt.op_manager._backends if b.name == "shm"][0]
    assert shm._map is None, "shm must not establish across fake hosts"
    assert not shm.enabled([], None)


def scenario_barrier(hvd, rank, size):
    import time
    t0 = time.monotonic()
    if rank == 0:
        time.sleep(0.5)
    hvd.barrier(name="b1")
    if rank != 0:
        assert time.monotonic() - t0 >= 0.4, "barrier did not block"


def scenario_shape_mismatch_error(hvd, rank, size):
    # (reference: test_horovod_allreduce_error, test_tensorflow.py:265)
    from horovod_tpu.common.status import HorovodInternalError
    shape = (4, 5) if rank == 0 else (4, 6)
    try:
        hvd.allreduce(np.ones(shape, np.float32), name="bad_shape")
    except HorovodInternalError as e:
        assert "shape" in str(e).lower()
    else:
        raise AssertionError("expected HorovodInternalError")
    # world must still be usable after an ERROR response
    out = hvd.allreduce(np.ones(3, np.float32), average=False,
                        name="after_err")
    np.testing.assert_allclose(out, size * np.ones(3))


def scenario_dtype_mismatch_error(hvd, rank, size):
    # (reference: test_tensorflow.py:293)
    from horovod_tpu.common.status import HorovodInternalError
    dt = np.float32 if rank == 0 else np.float64
    try:
        hvd.allreduce(np.ones(4, dt), name="bad_dtype")
    except HorovodInternalError as e:
        assert "data type" in str(e).lower()
    else:
        raise AssertionError("expected HorovodInternalError")


def scenario_root_rank_mismatch_error(hvd, rank, size):
    # (reference: test_tensorflow.py:708)
    from horovod_tpu.common.status import HorovodInternalError
    try:
        hvd.broadcast(np.ones(4), root_rank=rank % size, name="bad_root")
    except HorovodInternalError as e:
        assert "root rank" in str(e).lower()
    else:
        raise AssertionError("expected HorovodInternalError")


def scenario_rank_subset_order(hvd, rank, size):
    """Out-of-order submission across ranks must still converge: rank 0
    submits a,b; rank 1 submits b,a — negotiation totals the order."""
    names = ["oo/a", "oo/b"] if rank == 0 else ["oo/b", "oo/a"]
    handles = {n: hvd.allreduce_async(np.full(5, float(rank), np.float32),
                                      average=False, name=n)
               for n in names}
    total = sum(range(size))
    for n, h in handles.items():
        np.testing.assert_allclose(hvd.synchronize(h),
                                   np.full(5, float(total)))


def scenario_hier_controller(hvd, rank, size):
    """Hierarchical control plane on a forced multihost topology
    (HOROVOD_HOSTNAME set by the harness): remote leaves must have
    migrated behind their host's local root, the coordinator must hold
    one channel per remote host, and every collective — hence every
    relayed control/data primitive, including broadcast from each kind
    of rank — must still be exact (control-plane analog of
    reference: horovod/common/operations.cc:729-764)."""
    from horovod_tpu.common import basics as _b

    rt = _b.runtime()
    ctl = rt.controller
    topo = ctl.topology
    assert topo.cross_size > 1, "scenario expects a multihost topology"
    if rank == 0:
        # Fan-in = host-0 leaves + one channel per remote host.
        expected_fanin = (topo.local_sizes[0] - 1) + (topo.cross_size - 1)
        assert len(ctl._channels) == expected_fanin, (
            len(ctl._channels), expected_fanin)
        assert ctl._has_aggregates, ctl._members
        agg = {o: ms for o, ms in ctl._members.items() if len(ms) > 1}
        assert agg, "no aggregate channels at the coordinator"
    elif topo.local_rank == 0:
        assert len(ctl._children) == topo.local_size - 1, ctl._children
    else:
        assert not ctl._children
        if topo.cross_rank != 0:
            # migrated: upward channel is the loopback root, not the
            # coordinator listener
            assert ctl._ch.sock.getpeername()[0] == "127.0.0.1"

    # allreduce incl. fusion through the aggregated gather
    handles = [hvd.allreduce_async(
        np.full(8, float(rank + 1) * (i + 1), np.float64),
        average=False, name=f"hc/ar{i}") for i in range(12)]
    ssum = sum(range(1, size + 1))
    for i, h in enumerate(handles):
        np.testing.assert_allclose(
            hvd.synchronize(h), np.full(8, ssum * (i + 1), np.float64))

    # variable-dim0 allgather (exercises per-rank sizes surviving the
    # aggregate frame unpack in rank order)
    out = hvd.allgather(np.full((rank + 1, 2), float(rank), np.float32),
                        name="hc/ag")
    off = 0
    for r in range(size):
        np.testing.assert_allclose(out[off:off + r + 1],
                                   np.full((r + 1, 2), float(r)))
        off += r + 1

    # broadcast from EVERY root: coordinator, host-0 leaf, remote
    # root, remote leaf — each takes a different relay branch
    for root in range(size):
        x = np.full((5,), float(rank * 10), np.float64)
        outb = hvd.broadcast(x, root_rank=root, name=f"hc/bc{root}")
        np.testing.assert_allclose(outb, np.full((5,), float(root * 10)))

    # alltoall + reducescatter + barrier over the relayed data plane
    per = 2
    x = np.arange(size * per, dtype=np.float32) + 100 * rank
    outa = hvd.alltoall(x, name="hc/a2a")
    expected = np.concatenate(
        [np.arange(rank * per, (rank + 1) * per) + 100 * src
         for src in range(size)]).astype(np.float32)
    np.testing.assert_allclose(outa, expected)

    x = np.arange(size * 3, dtype=np.float32) * (rank + 1)
    outr = hvd.reducescatter(x, name="hc/rs")
    np.testing.assert_allclose(
        outr, np.arange(rank * 3, (rank + 1) * 3) * ssum)

    hvd.barrier(name="hc/bar")


def scenario_flat_controller_multihost(hvd, rank, size):
    """With HOROVOD_TPU_HIER_CONTROLLER=0 a multihost topology keeps
    the flat star: every worker stays directly connected to the
    coordinator and no aggregate channels exist."""
    from horovod_tpu.common import basics as _b

    ctl = _b.runtime().controller
    assert ctl.topology.cross_size > 1
    if rank == 0:
        assert len(ctl._channels) == size - 1, len(ctl._channels)
        assert not ctl._has_aggregates
    else:
        assert not ctl._children
    out = hvd.allreduce(np.full(6, float(rank + 1), np.float32),
                        average=False, name="flat/ar")
    np.testing.assert_allclose(
        out, np.full(6, sum(range(1, size + 1)), np.float32))
    hvd.barrier(name="flat/bar")


def scenario_topology(hvd, rank, size):
    assert hvd.rank() == rank
    assert hvd.size() == size
    # all ranks in these tests run on one host
    assert hvd.local_size() == size
    assert hvd.local_rank() == rank
    assert hvd.cross_size() == 1
    assert hvd.is_homogeneous()


def scenario_stall_shutdown(hvd, rank, size):
    """Rank 1 never submits; stall inspector must shut the job down
    (reference analog: test/test_stall.py)."""
    from horovod_tpu.common.status import HorovodInternalError
    if rank == 0:
        try:
            hvd.allreduce(np.ones(4, np.float32), name="stalled")
        except HorovodInternalError:
            return
        raise AssertionError("expected stall shutdown error")
    else:
        import time
        time.sleep(5.0)




def scenario_torch_optimizer(hvd_mod, rank, size):
    """torch adapter end-to-end: broadcast params, hook-driven async
    grad allreduce, optimizer-state broadcast (reference analog:
    test_torch.py:802-1003 + the DistributedOptimizer flow)."""
    import torch
    import horovod_tpu.torch as hvd

    torch.manual_seed(100 + rank)  # rank-divergent init on purpose
    model = torch.nn.Sequential(
        torch.nn.Linear(6, 4), torch.nn.ReLU(), torch.nn.Linear(4, 2))
    opt = torch.optim.SGD(model.parameters(), lr=0.05, momentum=0.9,
                          weight_decay=1e-4)

    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    # after broadcast all ranks agree parameter-wise
    flat = torch.cat([p.detach().reshape(-1) for p in model.parameters()])
    gathered = hvd.allgather(flat.reshape(1, -1), name="check.init")
    for r in range(size):
        assert torch.allclose(gathered[r], gathered[0]), "params diverged"

    dopt = hvd.DistributedOptimizer(
        opt, named_parameters=model.named_parameters())
    torch.manual_seed(1234 + rank)
    for step in range(3):
        x = torch.randn(8, 6)
        y = torch.randn(8, 2)
        dopt.zero_grad()
        loss = torch.nn.functional.mse_loss(model(x), y)
        loss.backward()
        dopt.step()
    flat = torch.cat([p.detach().reshape(-1) for p in model.parameters()])
    gathered = hvd.allgather(flat.reshape(1, -1), name="check.final")
    for r in range(size):
        assert torch.allclose(gathered[r], gathered[0], atol=1e-6), \
            "rank-divergent data should still yield identical params"

    hvd.broadcast_optimizer_state(opt, root_rank=0)
    g = opt.param_groups[0]
    assert g["lr"] == 0.05 and g["momentum"] == 0.9
    assert abs(g["weight_decay"] - 1e-4) < 1e-12
    assert isinstance(g.get("nesterov", False), bool)


def scenario_torch_allreduce_grad(hvd_mod, rank, size):
    """Gradient flows THROUGH hvd.allreduce (reference:
    test_horovod_allreduce_grad, test_torch.py:377): the backward of a
    sum-allreduce sums the upstream gradients, average averages them."""
    import torch
    import horovod_tpu.torch as hvd

    x = torch.full((5,), float(rank + 1), requires_grad=True)
    y = hvd.allreduce(x, op=hvd.Sum, name="g.sum")
    assert torch.allclose(y, torch.full((5,),
                                        float(sum(range(1, size + 1)))))
    y.sum().backward()
    # upstream ones, sum-allreduced across ranks -> size
    assert torch.allclose(x.grad, torch.full((5,), float(size))), x.grad

    x2 = torch.full((3,), float(rank + 1), requires_grad=True)
    hvd.allreduce(x2, op=hvd.Average, name="g.avg").sum().backward()
    # upstream ones, averaged -> ones
    assert torch.allclose(x2.grad, torch.ones(3)), x2.grad

    # no-grad tensors keep the plain (non-autograd) path
    z = torch.full((4,), float(rank + 1))
    out = hvd.allreduce(z, op=hvd.Sum, name="g.nograd")
    assert not out.requires_grad

    # double backward (gradient-penalty style): when the upstream
    # gradient itself carries a graph (nonlinear loss), the backward
    # recursion must keep it differentiable instead of silently
    # cutting the second order at the collective
    ssum = sum(range(1, size + 1))
    x3 = torch.full((2,), float(rank + 1), requires_grad=True)
    y3 = hvd.allreduce(x3, op=hvd.Sum, name="g.dd")
    loss = (y3 ** 2).sum()
    (g,) = torch.autograd.grad(loss, x3, create_graph=True)
    # g = sum-allreduce(2*y3) = 2 * size * ssum  (y3 == ssum everywhere)
    assert torch.allclose(g, torch.full((2,), 2.0 * size * ssum)), g
    assert g.requires_grad, "create_graph lost through the collective"
    (g2,) = torch.autograd.grad(g.sum(), x3)
    # two nested sum-allreduces of ones: 2 * size * size
    assert torch.allclose(g2, torch.full((2,), 2.0 * size * size)), g2


def scenario_torch_adam_state(hvd_mod, rank, size):
    """broadcast_optimizer_state with tuple hyperparameters (Adam's
    betas) and materialized per-param state incl. int step counters —
    tuples must be rebuilt, not assigned into (reference analog:
    test_torch.py:802-1003 covering every optimizer class)."""
    import torch
    import horovod_tpu.torch as hvd

    torch.manual_seed(200 + rank)
    model = torch.nn.Linear(5, 3)
    # rank-divergent hyperparams: the broadcast must impose rank 0's
    betas = (0.9, 0.999) if rank == 0 else (0.5, 0.7)
    lr = 1e-3 if rank == 0 else 0.1
    opt = torch.optim.Adam(model.parameters(), lr=lr, betas=betas,
                           amsgrad=False)
    # materialize state (exp_avg tensors + int step counters)
    loss = model(torch.randn(4, 5)).sum()
    loss.backward()
    opt.step()

    hvd.broadcast_optimizer_state(opt, root_rank=0)
    g = opt.param_groups[0]
    assert isinstance(g["betas"], tuple), type(g["betas"])
    assert g["betas"] == (0.9, 0.999), g["betas"]
    assert abs(g["lr"] - 1e-3) < 1e-12, g["lr"]
    # tensor state agrees world-wide after broadcast
    for pid, st in opt.state_dict()["state"].items():
        for key, val in st.items():
            if isinstance(val, torch.Tensor):
                gathered = hvd.allgather(
                    val.detach().reshape(1, -1).to(torch.float32),
                    name=f"check.adam.{pid}.{key}")
                for r in range(size):
                    assert torch.allclose(gathered[r], gathered[0]), \
                        f"state {pid}/{key} diverged"


def scenario_torch_opt_state_asymmetric(hvd_mod, rank, size):
    """The checkpoint-restore shape broadcast_optimizer_state exists
    for: ONLY rank 0 has materialized state (it "loaded a checkpoint");
    workers hold fresh optimizers. Without empty-state materialization
    (reference: horovod/torch/__init__.py:249-271) rank 0 submits
    broadcasts the workers never submit and the world hangs."""
    import torch
    import horovod_tpu.torch as hvd

    torch.manual_seed(300 + rank)
    model = torch.nn.Linear(4, 2)
    # A frozen parameter: real training on rank 0 never gives it a
    # gradient, so rank 0's state has NO entry for it. Worker-side
    # materialization must also skip it or the broadcast structures
    # disagree and the world hangs.
    model.bias.requires_grad_(False)
    opt = torch.optim.Adam(model.parameters(), lr=1e-3)
    if rank == 0:
        # rank 0 materializes real (non-zero) state
        loss = model(torch.randn(3, 4)).sum()
        loss.backward()
        opt.step()
        opt.zero_grad()
    assert bool(opt.state_dict()["state"]) == (rank == 0)

    hvd.broadcast_optimizer_state(opt, root_rank=0)

    st = opt.state_dict()["state"]
    assert st, "workers must have materialized state after broadcast"
    for pid, entry in st.items():
        for key, val in entry.items():
            if isinstance(val, torch.Tensor) and val.numel():
                gathered = hvd.allgather(
                    val.detach().reshape(1, -1).to(torch.float32),
                    name=f"check.asym.{pid}.{key}")
                for r in range(size):
                    assert torch.allclose(gathered[r], gathered[0]), \
                        f"state {pid}/{key} diverged after restore bcast"

    # Stateless optimizer: every rank takes the early return, no wire
    # traffic, no hang (reference :266-271).
    sgd = torch.optim.SGD(model.parameters(), lr=0.1)
    hvd.broadcast_optimizer_state(sgd, root_rank=0)
    assert not sgd.state_dict()["state"]

    # LBFGS is rejected up front on every rank (reference :241-245),
    # including when hidden behind the DistributedOptimizer wrapper.
    lbfgs = torch.optim.LBFGS([p for p in model.parameters()
                               if p.requires_grad])
    for candidate in (lbfgs, hvd.DistributedOptimizer(lbfgs)):
        try:
            hvd.broadcast_optimizer_state(candidate, root_rank=0)
        except ValueError:
            pass
        else:
            raise AssertionError("LBFGS broadcast must raise ValueError")

    # world still healthy after the error path
    one = hvd.allreduce(torch.ones(2), name="asym.final", op=hvd.Sum)
    assert torch.allclose(one, torch.full((2,), float(size)))


def scenario_jax_adapter(hvd_mod, rank, size):
    """jax adapter host path: pytree gradient allreduce + parameter
    broadcast through the background runtime."""
    import horovod_tpu.jax as hvd

    grads = {"w": np.full((3, 2), float(rank + 1), np.float32),
             "b": np.full((2,), float(rank + 1), np.float32)}
    out = hvd.allreduce_gradients(grads, op=hvd.Average)
    mean = sum(range(1, size + 1)) / size
    np.testing.assert_allclose(out["w"], mean)
    np.testing.assert_allclose(out["b"], mean)

    params = {"w": np.full((4,), float(rank), np.float32)}
    out = hvd.broadcast_parameters(params, root_rank=1)
    np.testing.assert_allclose(out["w"], 1.0)

    comp = hvd.allreduce_gradients(
        {"g": np.full((8,), float(rank + 1), np.float32)},
        op=hvd.Average, compression=hvd.Compression.fp16)
    np.testing.assert_allclose(comp["g"], mean, rtol=1e-3)



def scenario_tf_sparse_as_dense(hvd_mod, rank, size):
    """sparse_as_dense=True must produce the same effective gradient
    as the IndexedSlices gather path, bit-for-bit on exactly
    representable values (reference:
    horovod/tensorflow/__init__.py:157,195-202). Uses overlapping AND
    duplicated indices so scatter-add summing is actually exercised."""
    import tensorflow as tf
    import horovod_tpu.tensorflow as hvd_tf

    # rank r touches rows {r, r+1} of a 4-row embedding, with row
    # r+1 duplicated — integer-valued floats keep both paths exact
    values = tf.constant(np.array(
        [[2.0 * (rank + 1)] * 3,
         [4.0 * (rank + 1)] * 3,
         [6.0 * (rank + 1)] * 3], np.float32))
    indices = tf.constant(np.array([rank, rank + 1, rank + 1], np.int64))
    dense_shape = tf.constant([size + 1, 3], tf.int64)

    def _make():
        return tf.IndexedSlices(values, indices, dense_shape=dense_shape)

    # gather path -> IndexedSlices; densify to compare
    sparse_out = hvd_tf.allreduce(_make(), op=hvd_tf.Average,
                                  name="sad.gather")
    assert isinstance(sparse_out, tf.IndexedSlices)
    via_gather = tf.scatter_nd(
        tf.expand_dims(sparse_out.indices, 1), sparse_out.values,
        dense_shape).numpy()

    # dense path -> plain tensor
    dense_out = hvd_tf.allreduce(_make(), op=hvd_tf.Average,
                                 name="sad.dense", sparse_as_dense=True)
    assert not isinstance(dense_out, tf.IndexedSlices)
    assert dense_out.shape == (size + 1, 3)

    np.testing.assert_array_equal(dense_out.numpy(), via_gather)

    # and through DistributedOptimizer(sparse_as_dense=True): the
    # applied update must equal the gather-path update exactly
    var = tf.Variable(np.zeros((size + 1, 3), np.float32))
    opt = hvd_tf.DistributedOptimizer(
        tf.keras.optimizers.SGD(1.0), sparse_as_dense=True)
    opt.apply_gradients([(_make(), var)])
    np.testing.assert_array_equal(var.numpy(), -via_gather)


def scenario_tf_broadcast_hook(hvd_mod, rank, size):
    """BroadcastGlobalVariablesHook must be a REAL SessionRunHook that
    broadcasts rank 0's variables through a TF1 MonitoredTrainingSession
    (reference: horovod/tensorflow/__init__.py:117-148)."""
    import tensorflow as tf
    tf.compat.v1.disable_eager_execution()
    import horovod_tpu.tensorflow as hvd_tf

    v = tf.compat.v1.get_variable(
        "v", initializer=np.full((3, 2), float(rank + 7), np.float32))
    hook = hvd_tf.BroadcastGlobalVariablesHook(0)
    assert isinstance(hook, tf.compat.v1.train.SessionRunHook), type(hook)
    with tf.compat.v1.train.MonitoredTrainingSession(
            hooks=[hook]) as sess:
        out = sess.run(v)
    np.testing.assert_allclose(out, np.full((3, 2), 7.0))


def scenario_keras_optimizer(hvd_mod, rank, size):
    """keras DistributedOptimizer: rank-divergent data, identical
    weights after fit (reference analog: test_keras.py:62-186 +
    test_tensorflow_keras.py:46 test_train_model)."""
    import os
    os.environ.setdefault("KERAS_BACKEND", "tensorflow")
    import keras
    import horovod_tpu.keras as hvd

    keras.utils.set_random_seed(42)  # same init everywhere
    model = keras.Sequential([
        keras.layers.Input((4,)),
        keras.layers.Dense(3, activation="relu"),
        keras.layers.Dense(2),
    ])
    opt = hvd.DistributedOptimizer(keras.optimizers.SGD(0.05))
    model.compile(optimizer=opt, loss="mse")
    rng = np.random.RandomState(rank)  # different data per rank
    x = rng.randn(16, 4).astype(np.float32)
    y = rng.randn(16, 2).astype(np.float32)
    model.fit(x, y, epochs=1, batch_size=8, verbose=0)

    flat = np.concatenate([w.reshape(-1) for w in model.get_weights()])
    gathered = hvd_mod.allgather(flat.reshape(1, -1), name="keras.check")
    for r in range(size):
        np.testing.assert_allclose(gathered[r], gathered[0], atol=1e-6)


def scenario_tfkeras_facade(hvd_mod, rank, size):
    """horovod_tpu.tensorflow.keras (the tf.keras facade, reference:
    horovod/tensorflow/keras/__init__.py): DistributedOptimizer +
    BroadcastGlobalVariablesCallback through model.fit, then a
    save -> load_model round trip that re-wraps the optimizer."""
    import os
    import tempfile
    os.environ.setdefault("KERAS_BACKEND", "tensorflow")
    import tensorflow as tf
    import horovod_tpu.tensorflow.keras as hvd

    tf.keras.utils.set_random_seed(100 + rank)  # divergent init
    model = tf.keras.Sequential([
        tf.keras.layers.Input((4,)),
        tf.keras.layers.Dense(3, activation="relu"),
        tf.keras.layers.Dense(2),
    ])
    opt = hvd.DistributedOptimizer(tf.keras.optimizers.SGD(0.05))
    model.compile(optimizer=opt, loss="mse")
    rng = np.random.RandomState(rank)
    x = rng.randn(16, 4).astype(np.float32)
    y = rng.randn(16, 2).astype(np.float32)
    # the broadcast callback must erase the divergent initialization
    model.fit(x, y, epochs=1, batch_size=8, verbose=0, callbacks=[
        hvd.callbacks.BroadcastGlobalVariablesCallback(0)])

    flat = np.concatenate([w.reshape(-1) for w in model.get_weights()])
    gathered = hvd_mod.allgather(flat.reshape(1, -1), name="tfk.check")
    for r in range(size):
        np.testing.assert_allclose(gathered[r], gathered[0], atol=1e-6)

    # save/load round trip restores a DISTRIBUTED optimizer; a plain
    # keras load of the same file must fail loudly (the reference's
    # failure mode, never a silently-undistributed optimizer)
    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "model.keras")
        model.save(path)
        loaded = hvd.load_model(path)
        assert getattr(loaded.optimizer, "_hvd_wrapped", False)
        try:
            tf.keras.models.load_model(path)
            raise AssertionError("plain load should fail loudly")
        except TypeError:
            pass

    # reference call shape broadcast_global_variables(root) fails with
    # guidance, not a confusing attribute error
    try:
        hvd.broadcast_global_variables(0)
        raise AssertionError("old call shape should raise TypeError")
    except TypeError as e:
        assert "BroadcastGlobalVariablesCallback" in str(e)


def scenario_tf_tape(hvd_mod, rank, size):
    """DistributedGradientTape averages grads across ranks
    (reference analog: test_tensorflow.py:334 allreduce_grad)."""
    import tensorflow as tf
    import horovod_tpu.tensorflow as hvd

    v = tf.Variable([1.0, 2.0, 3.0])
    with hvd.DistributedGradientTape(tf.GradientTape()) as tape:
        loss = tf.reduce_sum(v * float(rank + 1))
    grads = tape.gradient(loss, [v])
    mean = sum(range(1, size + 1)) / size
    np.testing.assert_allclose(grads[0].numpy(), [mean] * 3, rtol=1e-6)

    bcast = tf.Variable([float(rank)] * 4)
    hvd.broadcast_variables([bcast], root_rank=1)
    np.testing.assert_allclose(bcast.numpy(), [1.0] * 4)


def scenario_tf_allreduce_grad(hvd_mod, rank, size):
    """Gradient flows through the standalone TF allreduce under
    GradientTape (reference: the registered HorovodAllreduce gradient,
    tensorflow/mpi_ops.py)."""
    import tensorflow as tf
    import horovod_tpu.tensorflow as hvd

    x = tf.constant([float(rank + 1)] * 4)
    with tf.GradientTape() as tape:
        tape.watch(x)
        y = hvd.allreduce(x, op=hvd.Sum, name="tg.ar")
        loss = tf.reduce_sum(y)
    assert np.allclose(y.numpy(), sum(range(1, size + 1)))
    g = tape.gradient(loss, x)
    # upstream ones, sum-allreduced -> size
    assert np.allclose(g.numpy(), float(size)), g.numpy()

    # average semantics in the gradient too
    x2 = tf.constant([float(rank + 1)] * 3)
    with tf.GradientTape() as tape:
        tape.watch(x2)
        loss = tf.reduce_sum(hvd.allreduce(x2, op=hvd.Average,
                                           name="tg.avg"))
    assert np.allclose(tape.gradient(loss, x2).numpy(), 1.0)

    # variables differentiate exactly like tensors
    v = tf.Variable([float(rank + 1)] * 2)
    with tf.GradientTape() as tape:
        loss = tf.reduce_sum(hvd.allreduce(v, op=hvd.Sum,
                                           name="tg.var"))
    assert np.allclose(tape.gradient(loss, v).numpy(),
                       float(size)), "variable gradient lost"

    # python scalars still work on the plain path
    s = hvd.allreduce(3.0 * (rank + 1), op=hvd.Sum, name="tg.scalar")
    assert np.allclose(np.asarray(s), 3.0 * sum(range(1, size + 1)))


def scenario_torch_gather_bcast_grad(hvd_mod, rank, size):
    """Gradients flow through torch allgather (variable dim-0) and
    broadcast (reference: HorovodAllgather / HorovodBroadcast autograd
    Functions, horovod/torch/mpi_ops.py:236-334)."""
    import torch
    import horovod_tpu.torch as hvd

    # -- allgather: rank r contributes r+1 rows of 2 ---------------------
    d0 = rank + 1
    x = torch.full((d0, 2), float(rank + 1), requires_grad=True)
    total_rows = sum(r + 1 for r in range(size))
    w = torch.arange(total_rows, dtype=torch.float32)[:, None] + 1.0
    y = hvd.allgather(x, name="tg.ag")
    assert y.shape == (total_rows, 2)
    (y * w).sum().backward()
    off = sum(r + 1 for r in range(rank))
    want = size * (np.arange(total_rows, dtype=np.float32)[:, None]
                   + 1.0)[off:off + d0]
    np.testing.assert_allclose(x.grad.numpy(),
                               np.broadcast_to(want, (d0, 2)))

    # -- broadcast: non-root inputs get exact zero gradient --------------
    root = size - 1
    v = torch.full((3,), float(rank + 10), requires_grad=True)
    yb = hvd.broadcast(v, root_rank=root, name="tg.bc")
    np.testing.assert_allclose(yb.detach().numpy(), float(root + 10))
    (yb * float(rank + 1)).sum().backward()
    ssum = sum(range(1, size + 1))
    if rank == root:
        np.testing.assert_allclose(v.grad.numpy(), float(ssum))
    else:
        np.testing.assert_allclose(v.grad.numpy(), 0.0)

    # broadcast_ stays in-place and non-differentiable, even on a
    # requires_grad leaf (the reference contract)
    p = torch.full((2,), float(rank), requires_grad=True)
    out = hvd.broadcast_(p, root_rank=0, name="tg.bc_")
    assert out is p and p.grad_fn is None
    np.testing.assert_allclose(p.detach().numpy(), 0.0)


def scenario_tf_gather_bcast_grad(hvd_mod, rank, size):
    """Gradients flow through TF allgather (variable dim-0!) and
    broadcast (reference: the registered HorovodAllgather /
    HorovodBroadcast gradients, tensorflow/mpi_ops.py:127-181):
    allgather's grad is this rank's slice of the sum-allreduced
    upstream; broadcast's grad is the summed upstream on the root and
    zeros elsewhere."""
    import tensorflow as tf
    import horovod_tpu.tensorflow as hvd

    # -- allgather: rank r contributes r+1 rows of 2 ---------------------
    d0 = rank + 1
    x = tf.constant(np.full((d0, 2), float(rank + 1), np.float32))
    # per-GLOBAL-row weights, identical on every rank
    total_rows = sum(r + 1 for r in range(size))
    w = tf.constant(np.arange(total_rows,
                              dtype=np.float32)[:, None] + 1.0)
    with tf.GradientTape() as tape:
        tape.watch(x)
        y = hvd.allgather(x, name="tg.ag")
        loss = tf.reduce_sum(y * w)
    assert y.shape == (total_rows, 2)
    g = tape.gradient(loss, x)
    # upstream dL/dy = w on every rank; sum over ranks = size * w;
    # our slice starts at sum of earlier ranks' sizes
    off = sum(r + 1 for r in range(rank))
    want = size * (np.arange(total_rows, dtype=np.float32)[:, None]
                   + 1.0)[off:off + d0]
    assert np.allclose(g.numpy(), want), (g.numpy(), want)

    # -- broadcast: non-root inputs get zero gradient --------------------
    root = size - 1
    v = tf.Variable(np.full(3, float(rank + 10), np.float32))
    with tf.GradientTape() as tape:
        y = hvd.broadcast(v, root_rank=root, name="tg.bc")
        loss = tf.reduce_sum(y * float(rank + 1))
    assert np.allclose(y.numpy(), float(root + 10))
    g = tape.gradient(loss, v)
    ssum = sum(range(1, size + 1))
    if rank == root:
        assert np.allclose(g.numpy(), float(ssum)), g.numpy()
    else:
        assert np.allclose(g.numpy(), 0.0), g.numpy()


def scenario_scalar_broadcast(hvd_mod, rank, size):
    """0-d tensors must round-trip broadcast with shape intact
    (regression: ascontiguousarray promotes 0-d to (1,))."""
    out = hvd_mod.broadcast(np.asarray(float(rank)), root_rank=1,
                            name="scalar")
    assert np.asarray(out).shape == (), np.asarray(out).shape
    assert float(np.asarray(out)) == 1.0


def scenario_checkpoint_resume(hvd_mod, rank, size):
    """rank-0 save + broadcast restore: every rank ends bit-identical
    (reference resume contract: rank-0 checkpoint + state broadcast,
    SURVEY section 5)."""
    import tempfile, os
    from horovod_tpu.utils import save_checkpoint, restore_checkpoint

    tmp = os.environ["HVD_TEST_CKPT_DIR"]
    state = {"w": np.full((4,), 7.5, np.float32) if rank == 0
             else np.zeros((4,), np.float32),
             "step": np.asarray(3, np.int64) if rank == 0
             else np.asarray(0, np.int64)}
    save_checkpoint(tmp, state, step=3)
    hvd_mod.barrier(name="after-save")
    target = {"w": np.zeros((4,), np.float32),
              "step": np.asarray(0, np.int64)}
    restored = restore_checkpoint(tmp, target=target, broadcast=True)
    np.testing.assert_allclose(np.asarray(restored["w"]), 7.5)
    assert int(np.asarray(restored["step"])) == 3


def _init_jax_distributed(rank, size):
    import os
    import jax
    jax.config.update("jax_platforms", "cpu")
    port = int(os.environ["HOROVOD_CONTROLLER_PORT"]) + 1000
    jax.distributed.initialize(f"127.0.0.1:{port}", num_processes=size,
                               process_id=rank)
    return jax


def scenario_xla_backend(hvd_mod, rank, size):
    """Collectives on jax arrays in a REAL multi-process JAX world:
    the XlaMeshBackend path (negotiation -> fused psum over the proc
    mesh), not the socket fallback."""
    jax = _init_jax_distributed(rank, size)
    import jax.numpy as jnp

    x = jnp.full((8,), float(rank + 1), jnp.float32)
    out = hvd_mod.allreduce(x, average=False, name="xla.ar")
    ssum = sum(range(1, size + 1))
    assert hasattr(out, "devices"), "output should stay a jax array"
    np.testing.assert_allclose(np.asarray(out), ssum)

    # fused batch (several tensors in one cycle -> one compiled psum)
    handles = [hvd_mod.allreduce_async(
        jnp.full((4,), float(rank + 1) * (i + 1), jnp.float32),
        average=False, name=f"xla.f/{i}") for i in range(8)]
    for i, h in enumerate(handles):
        np.testing.assert_allclose(
            np.asarray(hvd_mod.synchronize(h)), ssum * (i + 1),
            rtol=1e-6)

    # broadcast with non-zero root (one-to-all collective-permute
    # path) — every root must deliver its own values
    for root in range(size):
        b = jnp.full((3,), float(rank * 10), jnp.float32)
        out = hvd_mod.broadcast(b, root_rank=root,
                                name=f"xla.bc/{root}")
        np.testing.assert_allclose(np.asarray(out), float(root * 10))
    # 0-d scalar broadcast rides the same path
    s = hvd_mod.broadcast(jnp.asarray(float(rank + 7)), root_rank=1,
                          name="xla.bc0d")
    np.testing.assert_allclose(np.asarray(s), 8.0)

    g = hvd_mod.allgather(
        jnp.full((rank + 1, 2), float(rank), jnp.float32), name="xla.ag")
    assert np.asarray(g).shape == (sum(range(1, size + 1)) + 0, 2) or         np.asarray(g).shape[0] == sum(r + 1 for r in range(size))

    # fused multi-entry allgather on the mesh: several variable-dim0
    # gathers submitted together execute as one padded all_gather +
    # per-entry slice (multi-entry execute_allgather)
    seen = _record_batches(hvd_mod)
    hs = [hvd_mod.allgather_async(
        jnp.full((rank + 1 + (i % 2), i + 1), float(rank * 10 + i),
                 jnp.float32), name=f"xla.fag.{i}") for i in range(6)]
    for i, h in enumerate(hs):
        out = np.asarray(hvd_mod.synchronize(h))
        total_rows = sum(r + 1 + (i % 2) for r in range(size))
        assert out.shape == (total_rows, i + 1), (i, out.shape)
        off = 0
        for r in range(size):
            rr = r + 1 + (i % 2)
            np.testing.assert_allclose(
                out[off:off + rr],
                np.full((rr, i + 1), float(r * 10 + i)))
            off += rr
    ag_batches = [names for kind, names in seen if kind == "ALLGATHER"]
    assert any(len(b) >= 2 for b in ag_batches), \
        f"no fused xla allgather batch: {ag_batches}"

    # empty entries inside the mesh path: one some-ranks-empty entry
    # (rank 0 contributes 0 rows) next to a normal one
    h1 = hvd_mod.allgather_async(
        jnp.full((rank, 2), float(rank), jnp.float32), name="xla.e.some")
    h2 = hvd_mod.allgather_async(
        jnp.full((2, 2), float(rank + 5), jnp.float32), name="xla.e.full")
    out = np.asarray(hvd_mod.synchronize(h1))
    assert out.shape == (sum(range(size)), 2), out.shape
    off = 0
    for r in range(size):
        np.testing.assert_allclose(out[off:off + r], float(r))
        off += r
    out = np.asarray(hvd_mod.synchronize(h2))
    for r in range(size):
        np.testing.assert_allclose(out[2 * r:2 * r + 2], float(r + 5))


def scenario_xla_async_overlap(hvd_mod, rank, size):
    """END-TO-END negotiation/execution overlap on the real XLA plane:
    a deliberately slow big collective (completion-observation delayed
    2.5 s) must not stop later cycles from negotiating, issuing, and
    COMPLETING smaller collectives through the real TCP gather — and
    rank 0's timeline must show the smalls' NEGOTIATE spans inside the
    big one's COLLECTIVE span (reference purpose: FinalizeCUDAQueue,
    cuda_operations.cc:148-179)."""
    import time as _t

    jax = _init_jax_distributed(rank, size)
    import jax.numpy as jnp
    from horovod_tpu.common import basics as _b

    # Warm the compiled paths AND measure this host's real round-trip
    # cost, so every timing bound below scales with the machine
    # instead of hard-coding wall-clock races.
    t0 = _t.monotonic()
    for i in range(3):
        hvd_mod.allreduce(jnp.full((4,), 1.0, jnp.float32),
                          average=False, name=f"ov.warm.{i}")
    rtt = max(0.05, (_t.monotonic() - t0) / 3)
    issue_wait = max(0.3, 3 * rtt)
    delay = max(2.5, 20 * rtt)

    rt = _b.runtime()
    xla = [b for b in rt.op_manager._backends if b.name == "xla_mesh"][0]
    orig_observe = xla._observe
    BIG = 1 << 16

    def slow_observe(outs):
        if any(getattr(o, "size", 0) >= BIG for o in outs):
            _t.sleep(delay)
        return orig_observe(outs)

    xla._observe = slow_observe

    ssum = sum(range(1, size + 1))
    h_big = hvd_mod.allreduce_async(
        jnp.full((BIG,), float(rank + 1), jnp.float32),
        average=False, name="ov.big")
    _t.sleep(issue_wait)  # let the big negotiate in its own cycle

    for i in range(3):
        out = hvd_mod.synchronize(hvd_mod.allreduce_async(
            jnp.full((4,), float(rank + 1 + i), jnp.float32),
            average=False, name=f"ov.small.{i}"))
        np.testing.assert_allclose(np.asarray(out), ssum + i * size)
    # the smalls completed end-to-end while the big is still in flight
    assert not hvd_mod.poll(h_big), \
        "big collective completed before its delay - no overlap proven"
    np.testing.assert_allclose(
        np.asarray(hvd_mod.synchronize(h_big)), ssum)

    hvd_mod.shutdown()  # flush the timeline writer
    if rank != 0:
        return
    from tests.trace_utils import (
        collective_span, load_trace, negotiate_start_ts,
    )
    _, by_name = load_trace(os.environ["HOROVOD_TIMELINE"])
    c_start, c_end = collective_span(by_name["ov.big"])
    assert c_end - c_start >= 0.8 * delay * 1e6, (c_start, c_end, delay)
    for i in range(3):
        neg = negotiate_start_ts(by_name[f"ov.small.{i}"])
        assert c_start < neg < c_end, (i, c_start, neg, c_end)


def scenario_xla_ragged_allgather(hvd_mod, rank, size):
    """Heavy dim-0 skew (one big rank, the rest tiny) must flip the
    fused allgather onto the masked-psum rendering — wire bytes track
    the true payload like MPI_Allgatherv (reference:
    mpi_operations.cc:95-173) — and still return exact rank-ordered
    rows; mild skew must stay on the padded all_gather."""
    jax = _init_jax_distributed(rank, size)
    import jax.numpy as jnp
    from horovod_tpu.common import basics as _b

    # skewed: rank 0 contributes 64 rows, everyone else 1
    rows = 64 if rank == 0 else 1
    x = jnp.full((rows, 3), float(rank), jnp.float32)
    out = hvd_mod.allgather(x, name="rag.skew")
    expected = np.concatenate(
        [np.full((64 if r == 0 else 1, 3), float(r), np.float32)
         for r in range(size)])
    np.testing.assert_allclose(np.asarray(out), expected)

    # uniform: stays on the padded all_gather path
    u = hvd_mod.allgather(
        jnp.full((2, 3), float(rank), jnp.float32), name="rag.uni")
    np.testing.assert_allclose(
        np.asarray(u),
        np.concatenate([np.full((2, 3), float(r), np.float32)
                        for r in range(size)]))

    # bool under the same skew: the psum rendering promotes to int
    # internally and must cast back — output dtype and values exact
    b = hvd_mod.allgather(
        jnp.full((rows, 2), rank % 2 == 0, jnp.bool_), name="rag.bool")
    assert np.asarray(b).dtype == np.bool_, np.asarray(b).dtype
    np.testing.assert_array_equal(
        np.asarray(b),
        np.concatenate([np.full((64 if r == 0 else 1, 2), r % 2 == 0,
                                np.bool_) for r in range(size)]))

    rt = _b.runtime()
    xla = [b for b in rt.op_manager._backends if b.name == "xla_mesh"][0]
    kinds = {k[0] for k in xla._cache}
    assert "allgather_psum" in kinds, kinds   # skewed case used psum
    assert "allgather" in kinds, kinds        # uniform case stayed padded


def scenario_xla_hierarchical(hvd_mod, rank, size):
    """HOROVOD_HIERARCHICAL_ALLREDUCE: allreduce rides the factored
    (cross, local) mesh (all ranks share this host -> cross=1,
    local=size; the factored-psum code path still executes)."""
    jax = _init_jax_distributed(rank, size)
    import jax.numpy as jnp
    from horovod_tpu.common import basics as _b

    x = jnp.full((6,), float(rank + 1), jnp.float32)
    out = hvd_mod.allreduce(x, average=True, name="hier.ar")
    np.testing.assert_allclose(np.asarray(out),
                               sum(range(1, size + 1)) / size)
    # the 2D mesh must actually have been built
    rt = _b.runtime()
    xla = [b for b in rt.op_manager._backends
           if b.name == "xla_mesh"][0]
    assert xla._mesh2d is not None, "hierarchical mesh not built"


def scenario_xla_hier_allreduce_multihost(hvd_mod, rank, size):
    """HOROVOD_HIERARCHICAL_ALLREDUCE on a forced 2-host topology
    (2 ranks per fake host): the factored (cross, local) psum must be
    the executable that actually compiled — a real two-level reduction,
    not the degenerate cross_size==1 shape — and values must match the
    flat path exactly (reference: NCCLHierarchicalAllreduce,
    nccl_operations.cc:167-372)."""
    assert size == 4, "scenario expects 4 ranks"
    jax = _init_jax_distributed(rank, size)
    import jax.numpy as jnp
    from horovod_tpu.common import basics as _b

    # exactly-representable values: the sum is bit-exact in f32
    # regardless of reduction order, so this matches the flat path
    # bit-for-bit.
    x = jnp.full((6,), float(2 ** rank), jnp.float32)
    out = hvd_mod.allreduce(x, average=False, name="hm.ar")
    expected = float(sum(2 ** r for r in range(size)))
    assert np.asarray(out).tolist() == [expected] * 6, np.asarray(out)

    # integer dtype: bitwise-exact by construction
    xi = np.full((5,), rank + 1, np.int32)
    outi = hvd_mod.allreduce(jnp.asarray(xi), average=False,
                             name="hm.ari")
    assert np.asarray(outi).tolist() == [10] * 5

    rt = _b.runtime()
    xla = [b for b in rt.op_manager._backends if b.name == "xla_mesh"][0]
    assert xla._mesh2d is not None, "hierarchical mesh not built"
    assert xla._mesh2d.shape["cross"] == 2 and \
        xla._mesh2d.shape["local"] == 2, dict(xla._mesh2d.shape)
    # the compiled executables must be the (cross, local) factored ones
    ar_axes = {k[4] for k in xla._cache if k[0] == "allreduce"}
    assert ("cross", "local") in ar_axes, ar_axes
    assert all(a == ("cross", "local") for a in ar_axes), ar_axes


def scenario_xla_hierarchical_allgather(hvd_mod, rank, size):
    """HOROVOD_HIERARCHICAL_ALLGATHER on a forced 2-host topology
    (HOROVOD_HOSTNAME set by the harness: ranks 0,1 on hostA; 2,3 on
    hostB): variable-dim0 allgather must take the two-level
    local-gather -> cross-exchange path and still return rank-ordered
    rows (reference: MPIHierarchicalAllgather,
    mpi_operations.cc:179-329)."""
    assert size == 4, "scenario expects 4 ranks"
    jax = _init_jax_distributed(rank, size)
    import jax.numpy as jnp
    from horovod_tpu.common import basics as _b

    # variable dim0: rank r contributes r+1 rows valued r
    x = jnp.full((rank + 1, 3), float(rank), jnp.float32)
    out = hvd_mod.allgather(x, name="hier.ag")
    expected = np.concatenate(
        [np.full((r + 1, 3), float(r), np.float32) for r in range(size)])
    np.testing.assert_allclose(np.asarray(out), expected)

    # FUSED multi-entry allgather on the two-level path: several
    # variable-dim0 gathers submitted together must land in one
    # (cross, local) gather and unpack per entry in rank order
    seen = _record_batches(hvd_mod)
    hs = [hvd_mod.allgather_async(
        jnp.full((rank + 1 + (i % 2), i + 1), float(rank * 10 + i),
                 jnp.float32), name=f"hier.fag.{i}") for i in range(4)]
    for i, h in enumerate(hs):
        got = np.asarray(hvd_mod.synchronize(h))
        off = 0
        for r in range(size):
            rr = r + 1 + (i % 2)
            np.testing.assert_allclose(
                got[off:off + rr],
                np.full((rr, i + 1), float(r * 10 + i)))
            off += rr
    fag_batches = [n for k, n in seen if k == "ALLGATHER"]
    assert any(len(b) >= 2 for b in fag_batches), fag_batches

    rt = _b.runtime()
    xla = [b for b in rt.op_manager._backends if b.name == "xla_mesh"][0]
    assert xla._mesh2d is not None, "hierarchical mesh not built"
    assert xla._mesh2d.shape["cross"] == 2 and \
        xla._mesh2d.shape["local"] == 2, dict(xla._mesh2d.shape)
    kinds = {k[0] for k in xla._cache}
    assert "allgather_hier" in kinds, kinds
    assert "allgather" not in kinds, kinds


def scenario_lockcheck_inversion(hvd, rank, size):
    """HOROVOD_TPU_LOCKCHECK armed world (the mp default): the
    runtime's instrumented locks must survive a real collective with
    zero false inversions, and a deliberately inverted synthetic pair
    must raise LockInversionError naming both orders — every rank."""
    from horovod_tpu.common import lockdep

    assert lockdep.enabled(), "mp worlds must arm HOROVOD_TPU_LOCKCHECK"
    before = lockdep.inversion_count()

    # real work first: the armed instrumentation must be invisible
    x = np.full(64, float(rank + 1), np.float64)
    out = hvd.allreduce(x, average=False, name="lc.warm")
    np.testing.assert_allclose(out, sum(range(1, size + 1)))
    assert lockdep.inversion_count() == before, \
        "healthy collective produced a lock inversion"

    # the runtime's core locks really are checked locks in this world
    from horovod_tpu.common import basics as _b
    tt_lock = _b.runtime().tensor_table._lock
    assert type(tt_lock).__name__ == "_CheckedLock", type(tt_lock)

    a = lockdep.lock("mp.sync.A")
    b = lockdep.lock("mp.sync.B")
    with a:
        with b:
            pass
    raised = False
    try:
        with b:
            with a:
                pass
    except lockdep.LockInversionError as e:
        raised = True
        assert "mp.sync.A" in str(e) and "mp.sync.B" in str(e), e
    assert raised, "inverted acquisition did not raise"
    assert lockdep.inversion_count() == before + 1

    # the world is still healthy after the caught inversion
    out = hvd.allreduce(x, average=False, name="lc.after")
    np.testing.assert_allclose(out, sum(range(1, size + 1)))


# -- elastic worlds (HOROVOD_ELASTIC=1; common/elastic.py) -------------
# A rank dies mid-collective; instead of the PR 2 fail-fast death
# sentence, the survivors re-rendezvous into a shrunk world and keep
# training. Victims die by fault injection (HOROVOD_FAULT_SPEC, set by
# the pytest wrappers); everything below asserts EXACT allreduce
# values against the current world size, so a post-resize step is
# bit-for-bit what a fresh world of that size would compute.

def _elastic_grad(b: int, ws_rank: int, n: int = 16) -> np.ndarray:
    """Deterministic integer-valued 'gradient': rank- and batch-
    dependent, so world sums are exactly computable for any size."""
    return np.full(n, float((ws_rank + 1) * (b % 7 + 1)), np.float32)


def _elastic_expected(b: int, ws: int, n: int = 16) -> np.ndarray:
    return np.full(n, float(sum(range(1, ws + 1)) * (b % 7 + 1)),
                   np.float32)


def _elastic_train(hvd, state, total: int, meta: dict):
    """The shared elastic training loop: one named steady allreduce
    per batch, params accumulated, batch committed. ``meta`` tracks
    world-size transitions, post-resize step counts and the recovery
    wall time (end of last good step -> end of resync)."""
    import time
    from horovod_tpu.common import elastic

    @elastic.run
    def train(state):
        while state.batch < total:
            ws = hvd.size()
            if meta["last_ws"] is None:
                meta["last_ws"] = ws
            elif ws != meta["last_ws"]:
                meta["resizes"].append((meta["last_ws"], ws,
                                        state.batch))
                if meta["t_last"] is not None:
                    meta["recovery_s"] = \
                        time.monotonic() - meta["t_last"]
                meta["last_ws"] = ws
            g = hvd.allreduce(_elastic_grad(state.batch, hvd.rank()),
                              average=False, name="eg")
            np.testing.assert_array_equal(
                g, _elastic_expected(state.batch, ws))
            state.params = state.params + g
            state.batch += 1
            state.commit()
            meta["t_last"] = time.monotonic()
            if meta["resizes"]:
                meta["post"] += 1

    train(state)


def _elastic_assert_world_coherent(hvd, state):
    """Every member's params must be identical after the run."""
    rows = hvd.allgather(state.params.reshape(1, -1), name="efp")
    for i in range(1, rows.shape[0]):
        np.testing.assert_array_equal(rows[i], rows[0])


def scenario_elastic_shrink(hvd, rank, size):
    """SIGKILL one rank mid-collective (fault spec set by the test):
    survivors re-rendezvous into ws-1, complete >= 20 more EXACT
    collectives (each equal to what a fresh shrunk world computes —
    the 'loss trajectory matches a never-killed world after resync'
    check), within 2x the heartbeat timeout, and end with identical
    params everywhere."""
    from horovod_tpu.common import elastic

    victim = size - 1
    hb = float(os.environ["HOROVOD_HEARTBEAT_TIMEOUT"])
    total = 40
    state = elastic.State(params=np.zeros(16, np.float32), batch=0)
    meta = {"last_ws": None, "t_last": None, "recovery_s": None,
            "post": 0, "resizes": []}
    _elastic_train(hvd, state, total, meta)

    ctx = elastic.context()
    assert ctx is not None
    assert hvd.size() == size - 1, hvd.size()
    assert len(meta["resizes"]) == 1 \
        and meta["resizes"][0][:2] == (size, size - 1), meta["resizes"]
    assert meta["post"] >= 20, meta
    assert ctx.membership.generation == 1, ctx.membership.generation
    assert meta["recovery_s"] is not None \
        and meta["recovery_s"] < 2 * hb, meta["recovery_s"]
    # the dead member is on the world-converged blacklist, attributed
    assert any(f"rank {victim}" in entry
               for entry in ctx.membership.blacklist), \
        ctx.membership.blacklist
    m = hvd.metrics()
    if m["enabled"]:
        # resize history rides the PR 4 plane: the local snapshot
        # shows the shrunk world everywhere, and the coordinator's
        # own counters record the barrier it ran
        assert m["local"]["hvd_world_size"]["v"] == size - 1, \
            m["local"]["hvd_world_size"]
        if hvd.rank() == 0:
            assert m["local"]["hvd_world_resizes_total"]["v"] >= 1, \
                m["local"].get("hvd_world_resizes_total")
    _elastic_assert_world_coherent(hvd, state)


def scenario_elastic_coordinator_death(hvd, rank, size):
    """SIGKILL rank 0 — coordinator AND controller socket. The lowest
    surviving rank (old rank 1) must win the deterministic election,
    run the barrier, and host the new world's controller; training
    continues exactly in the shrunk world."""
    from horovod_tpu.common import elastic

    old_rank = rank
    total = 40
    state = elastic.State(params=np.zeros(16, np.float32), batch=0)
    meta = {"last_ws": None, "t_last": None, "recovery_s": None,
            "post": 0, "resizes": []}
    _elastic_train(hvd, state, total, meta)

    ctx = elastic.context()
    assert hvd.size() == size - 1, hvd.size()
    assert meta["post"] >= 20, meta
    # dense re-ranking: old rank r -> new rank r-1; old rank 1 is the
    # re-elected coordinator
    assert hvd.rank() == old_rank - 1, (old_rank, hvd.rank())
    assert ctx.membership.generation == 1
    assert any("rank 0" in entry for entry in ctx.membership.blacklist)
    _elastic_assert_world_coherent(hvd, state)


def scenario_elastic_double_fault(hvd, rank, size):
    """Two-stage failure: one rank SIGKILLed mid-collective, a SECOND
    rank SIGKILLed on entry to the re-rendezvous barrier (fault
    trigger rdzv=1). The barrier must wait out its window for the
    silent second victim and close with the remaining survivors —
    recovery survives a fault DURING recovery."""
    from horovod_tpu.common import elastic

    total = 30
    state = elastic.State(params=np.zeros(16, np.float32), batch=0)
    meta = {"last_ws": None, "t_last": None, "recovery_s": None,
            "post": 0, "resizes": []}
    _elastic_train(hvd, state, total, meta)

    ctx = elastic.context()
    assert hvd.size() == size - 2, hvd.size()
    assert meta["post"] >= 10, meta
    assert ctx.membership.generation == 1
    assert len(ctx.membership.blacklist) == 2, ctx.membership.blacklist
    _elastic_assert_world_coherent(hvd, state)


def scenario_elastic_rejoin(hvd, rank, size):
    """Shrink, then GROW back: one rank is SIGKILLed, the survivors
    re-form at ws-1, and the (old) rank 0 respawns a fresh joiner
    process which rejoins at the next rendezvous barrier, resyncs the
    State by broadcast, and trains to completion in lockstep. Also
    runs as the JOINER itself (spawned with HOROVOD_ELASTIC_JOIN=1)."""
    import subprocess
    import sys as _sys
    import time
    from horovod_tpu.common import elastic

    ctx = elastic.context()
    joiner = ctx is not None and ctx.joined_as_rejoiner
    total = 50
    state = elastic.State(params=np.zeros(16, np.float32), batch=0)
    meta = {"last_ws": None, "t_last": None, "recovery_s": None,
            "post": 0, "resizes": []}
    child = {}

    from horovod_tpu.common import elastic as _e

    @_e.run
    def train(state):
        # Lockstep predicate shared by survivors AND the joiner: keep
        # training until the batch budget is spent AND the world has
        # grown back — every member sees the same (synced batch,
        # world size) pair, so everyone exits the same iteration.
        while state.batch < total or hvd.size() < size:
            ws = hvd.size()
            if meta["last_ws"] is None:
                meta["last_ws"] = ws
            elif ws != meta["last_ws"]:
                meta["resizes"].append((meta["last_ws"], ws,
                                        state.batch))
                meta["last_ws"] = ws
            if not joiner and hvd.rank() == 0 and ws == size - 1 \
                    and "proc" not in child:
                # The supervision-loop stand-in: respawn the lost slot
                # as a joiner pointed at this rank's elastic listener.
                env = dict(os.environ)
                env.pop("HOROVOD_FAULT_SPEC", None)
                env["HOROVOD_ELASTIC_JOIN"] = "1"
                env["HOROVOD_ELASTIC_JOIN_ADDR"] = "127.0.0.1"
                env["HOROVOD_ELASTIC_JOIN_PORT"] = str(ctx.port)
                child["proc"] = subprocess.Popen(
                    [_sys.executable, "-m", "tests.mp_scenarios",
                     "elastic_rejoin", "9", str(size), "0"], env=env)
            g = hvd.allreduce(_elastic_grad(state.batch, hvd.rank()),
                              average=False, name="eg")
            np.testing.assert_array_equal(
                g, _elastic_expected(state.batch, hvd.size()))
            state.params = state.params + g
            state.batch += 1
            state.commit()
            if meta["resizes"]:
                meta["post"] += 1

    train(state)

    assert hvd.size() == size, (hvd.size(), size)  # grown back
    ctx2 = elastic.context()
    if joiner:
        assert ctx2.joined_as_rejoiner
        assert ctx2.membership.generation >= 2
    else:
        # shrink first; the grow transition may land exactly on the
        # loop-exit edge (survivors can finish the batch budget while
        # the joiner is still starting up), so assert it through the
        # final world state rather than an observed body iteration.
        assert meta["resizes"] and \
            meta["resizes"][0][:2] == (size, size - 1), meta["resizes"]
        assert ctx2.membership.generation == 2, \
            ctx2.membership.generation
        if hvd.rank() == 0:
            assert ctx2.rejoins_admitted == 1, ctx2.rejoins_admitted
    _elastic_assert_world_coherent(hvd, state)
    if "proc" in child:
        rc = child["proc"].wait(timeout=60)
        assert rc == 0, f"joiner exited {rc}"


def scenario_elastic_disabled_fail_fast(hvd, rank, size):
    """Without HOROVOD_ELASTIC, elastic.run is a transparent wrapper:
    the PR 2 WorldAbortedError propagates verbatim — fail-fast
    behavior unchanged."""
    from horovod_tpu.common import elastic
    from horovod_tpu.common.status import WorldAbortedError

    assert elastic.context() is None
    state = elastic.State(params=np.zeros(8, np.float32), batch=0)

    @elastic.run
    def train(state):
        while state.batch < 1000:
            hvd.allreduce(np.ones(8, np.float32), average=False,
                          name="eg")
            state.batch += 1

    try:
        train(state)
        raise AssertionError("fault-injected world must abort")
    except WorldAbortedError as e:
        assert e.origin_rank == 1, e


def scenario_selfop_preempt(hvd, rank, size):
    """Proactive drain on a preemption notice (common/selfop.py): a
    ``preempt`` fault SIGTERMs one rank mid-training with a grace
    window. The supervision tick on that rank turns the notice into a
    resolved world abort, the rank drains to its last commit and
    retires with exit 0 (never reaching the post-train asserts), and
    the SURVIVORS resize to ws-1 with zero lost steps — every
    post-resize collective bit-exact vs a fresh shrunk world — all
    inside the grace window, before the SIGKILL backstop."""
    from horovod_tpu.common import elastic, selfop

    victim = size - 1
    hb = float(os.environ["HOROVOD_HEARTBEAT_TIMEOUT"])
    # a batch costs >= 1 negotiation cycle, so the cycle-40 fault
    # lands before batch 40 and >= 40 post-resize batches remain
    total = 80
    state = elastic.State(params=np.zeros(16, np.float32), batch=0)
    meta = {"last_ws": None, "t_last": None, "recovery_s": None,
            "post": 0, "resizes": []}
    _elastic_train(hvd, state, total, meta)

    # The preempted rank exits 0 inside the wrapper (retire path) —
    # only survivors get here.
    assert rank != victim, "preempted rank must retire before this"
    ctx = elastic.context()
    assert hvd.size() == size - 1, hvd.size()
    assert len(meta["resizes"]) == 1 \
        and meta["resizes"][0][:2] == (size, size - 1), meta["resizes"]
    assert meta["post"] >= 20, meta
    assert ctx.membership.generation == 1, ctx.membership.generation
    assert meta["recovery_s"] is not None \
        and meta["recovery_s"] < 2 * hb, meta["recovery_s"]
    # the resize is ATTRIBUTED to the supervision policy, not to a
    # death: the world-converged cause names the drain
    assert "selfop-preempt" in ctx.last_resize_cause, \
        ctx.last_resize_cause
    assert any(f"rank {victim}" in entry
               for entry in ctx.membership.blacklist), \
        ctx.membership.blacklist
    # the verdict plane rode the rendezvous on every member: a resize
    # with no pending demotion installs the EMPTY verdict for this
    # generation (stale pacing cannot leak across resizes)
    v = selfop.verdict()
    assert v.kind == "" and v.generation == 1, (v.kind, v.generation)
    assert selfop.cycle_pace_s(hvd.rank()) == 0.0
    m = hvd.metrics()
    if m["enabled"]:
        assert m["local"]["hvd_world_size"]["v"] == size - 1, \
            m["local"]["hvd_world_size"]
    _elastic_assert_world_coherent(hvd, state)


def scenario_selfop_demote(hvd, rank, size):
    """Telemetry-driven demotion (common/selfop.py): a persistent
    ``delay`` fault makes one launch rank the habitual last-arriver.
    After the churn cooldown the coordinator's supervision policy
    reads the straggler attribution window, demotes that rank to the
    ring tail via a same-size resize, and every member installs the
    identical demote verdict (world-replicated) with a pacing hint.
    Post-resize, non-demoted ranks pace their cycle top and the
    demoted rank's last-arriver share drops below the trigger —
    the skew measurably improves."""
    import re as _re
    import time

    from horovod_tpu.common import basics as _b
    from horovod_tpu.common import elastic, selfop

    old_rank = rank
    straggler = 1  # launch rank carrying the delay fault
    state = elastic.State(params=np.zeros(16, np.float32), batch=0)
    meta = {"post": 0}

    @elastic.run
    def train(state):
        # Lockstep predicate: the verdict installs at the SAME resize
        # on every member and training resumes from the same commit,
        # so the post-demotion counter stays identical everywhere and
        # every rank exits the same iteration. Keep the post window
        # under the 5s churn cooldown so no second verdict can fire.
        while True:
            if selfop.verdict().kind == "demote":
                meta["post"] += 1
                if meta["post"] > 60:
                    break
            elif state.batch > 4000:
                raise AssertionError(
                    f"no demotion after {state.batch} batches")
            g = hvd.allreduce(_elastic_grad(state.batch, hvd.rank()),
                              average=False, name="eg")
            np.testing.assert_array_equal(
                g, _elastic_expected(state.batch, hvd.size()))
            state.params = state.params + g
            state.batch += 1
            state.commit()

    train(state)

    ctx = elastic.context()
    assert hvd.size() == size, hvd.size()  # same size, reordered
    assert ctx.membership.generation == 1, ctx.membership.generation
    assert "selfop-demote" in ctx.last_resize_cause, \
        ctx.last_resize_cause
    # every member holds the IDENTICAL verdict (world-replicated)
    v = selfop.verdict()
    assert v.kind == "demote", v.kind
    assert v.target_rank == size - 1, v.target_rank  # ring tail
    assert v.pace_us > 0, v.pace_us
    assert v.generation == 1, v.generation
    rows = hvd.allgather(
        np.array([[v.target_rank, v.pace_us, v.generation]],
                 dtype=np.int64), name="sd.v")
    for i in range(1, size):
        np.testing.assert_array_equal(rows[i], rows[0])
    # dense renumbering: the straggler moved to the tail, everyone
    # after it shifted down one, everyone before it kept their rank
    if old_rank == straggler:
        assert hvd.rank() == size - 1, hvd.rank()
    elif old_rank > straggler:
        assert hvd.rank() == old_rank - 1, (old_rank, hvd.rank())
    else:
        assert hvd.rank() == old_rank, (old_rank, hvd.rank())
    # pacing applies to every member EXCEPT the demoted tail
    pace = selfop.cycle_pace_s(hvd.rank())
    if hvd.rank() == size - 1:
        assert pace == 0.0, pace
    else:
        assert pace > 0.0, pace
    if hvd.rank() == 0:
        assert selfop.decision_counts().get("demote") == 1, \
            selfop.decision_counts()
        # skew improves: the pre-demotion last-arriver share is in the
        # policy's decision line; the post-resize attribution window
        # (fresh tracker, >= 60 paced gathers) must show the demoted
        # rank below it — and below the trigger threshold
        pol = selfop.policy()
        m = _re.search(r"share=([0-9.]+)", pol._last_line)
        assert m, pol._last_line
        share_pre = float(m.group(1))
        assert share_pre >= 0.6, share_pre
        stats = _b.runtime()._straggler.window_stats()
        window = stats["window"]
        assert window >= 40, stats
        share_post = stats["last_counts"].get(size - 1, 0) / window
        assert share_post < share_pre, (share_post, share_pre, stats)
        assert share_post < 0.6, (share_post, stats)
    _elastic_assert_world_coherent(hvd, state)


# ---------------------------------------------------------------------------
# Multi-tenant collective service (common/tenancy.py,
# docs/multitenancy.md): concurrent sub-worlds on one fleet under QoS
# scheduling, fault isolation between tenants, and service-mode
# attach/detach with the parameter-snapshot broadcast fanout.
# ---------------------------------------------------------------------------

def _tenant_steps(tenant, rank, size, key, steps, numel=32):
    """Drive ``steps`` deterministic allreduces on ``tenant`` and
    assert exactness per step; returns the outputs."""
    ssum = sum(range(1, size + 1))
    outs = []
    for i in range(steps):
        out = tenant.allreduce(
            np.full(numel, float(rank + 1) * (i + 1), np.float32),
            average=False, name=f"{key}.g")
        np.testing.assert_allclose(out, ssum * (i + 1))
        outs.append(np.asarray(out))
    return outs


def scenario_tenants_exact(hvd, rank, size):
    """Two equal-weight tenants spanning the SAME ws=4 fleet train
    concurrently from separate threads; each tenant's per-step results
    are exact, and tenant A's sequence replayed AFTER the concurrent
    phase (B idle) is bit-identical — co-tenancy never perturbs
    numerics. Also asserts the per-tenant observability surfaces."""
    import threading
    ta = hvd.create_tenant("jobA", list(range(size)))
    tb = hvd.create_tenant("jobB", list(range(size)))
    assert ta.rank == rank and ta.size == size
    assert ta.world_id != tb.world_id
    results = {}

    def run(t, key):
        results[key] = _tenant_steps(t, rank, size, key, 30)

    threads = [threading.Thread(target=run, args=(t, k))
               for t, k in ((ta, "a"), (tb, "b"))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(results["a"]) == 30 and len(results["b"]) == 30

    # single-tenant replay of A's exact submission sequence, B idle:
    # bit-identical outputs prove scheduling never touched the math
    ssum = sum(range(1, size + 1))
    for i in range(30):
        out = ta.allreduce(
            np.full(32, float(rank + 1) * (i + 1), np.float32),
            average=False, name="replay.g")
        assert (np.asarray(out) == results["a"][i]).all(), i
        np.testing.assert_allclose(out, ssum * (i + 1))

    # per-tenant observability: lane stats flow, and the stall-report
    # world line carries the tenant identity + scheduler verdicts
    for t, key in ((ta, "jobA"), (tb, "jobB")):
        stats = t.lane_stats()
        assert stats["cycles"] >= 30, (key, stats)
        line = t._runtime._world_status_line()
        assert f"tenant {key}" in line and "weight" in line, line
    # the default world is untouched by tenant traffic
    out = hvd.allreduce(np.full(4, float(rank), np.float64),
                        average=False, name="dflt")
    np.testing.assert_allclose(out, sum(range(size)))
    ta.shutdown()
    tb.shutdown()


def scenario_tenants_tp_dp(hvd, rank, size):
    """A TENSOR-parallel tenant and a DATA-parallel tenant sharing one
    ws=4 fleet (the parallel-strategy composition ROADMAP names as
    unlocked by tenancy): the TP tenant drives Megatron-style
    row-parallel partial-sum allreduces plus column-parallel
    allgathers, the DP tenant drives averaged gradient allreduces.
    Both run concurrently from separate threads; every step of each is
    EXACT (integer-valued operands make float order irrelevant), and
    QoS isolation holds: each lane accounts its own cycles, the TP
    sequence replayed solo after the concurrent phase is bit-identical
    (co-scheduling never perturbed the math), and the default world is
    untouched."""
    import threading
    tp = hvd.create_tenant("tp", list(range(size)), weight=2.0)
    dp = hvd.create_tenant("dp", list(range(size)))
    assert tp.world_id != dp.world_id
    steps = 20
    # integer-valued operands: partial products and sums are exact in
    # f32 no matter the reduction order
    rng = np.random.RandomState(123)  # same seed on every rank
    A = rng.randint(-3, 4, size=(4, 8)).astype(np.float32)
    B = rng.randint(-3, 4, size=(8, 6)).astype(np.float32)
    assert 8 % size == 0 and 6 % 3 == 0
    k = 8 // size  # row-parallel contraction shard
    want_full = A @ B
    results = {"tp": [], "dp": []}

    def run_tp():
        for i in range(steps):
            # row-parallel: each rank holds a K-shard of the
            # contraction; the allreduce-sum of partials IS the matmul
            part = (A[:, rank * k:(rank + 1) * k]
                    @ B[rank * k:(rank + 1) * k, :]) * (i + 1)
            out = tp.allreduce(part, average=False, name="tp.row")
            np.testing.assert_array_equal(
                np.asarray(out), want_full * (i + 1))
            results["tp"].append(np.asarray(out))
            # column-parallel: activations gathered along features
            g = tp.allgather(
                np.full((2, 3), float(rank * 10 + i), np.float32),
                name="tp.col")
            g = np.asarray(g)
            assert g.shape == (2 * size, 3)
            np.testing.assert_array_equal(
                g, np.repeat(np.arange(size) * 10.0 + i, 2)
                .astype(np.float32)[:, None] * np.ones(3, np.float32))

    def run_dp():
        for i in range(steps):
            # gradient averaging: mean over ranks, exact for /4
            grad = np.full(64, float((rank + 1) * (i + 1)), np.float32)
            out = dp.allreduce(grad, average=True, name="dp.grad")
            want = sum(range(1, size + 1)) * (i + 1) / size
            np.testing.assert_array_equal(np.asarray(out), want)
            results["dp"].append(np.asarray(out))

    threads = [threading.Thread(target=run_tp),
               threading.Thread(target=run_dp)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(results["tp"]) == steps and len(results["dp"]) == steps

    # QoS isolation: per-lane accounting is independent (each lane saw
    # at least its own steps' cycles), and the scheduler's status
    # surface names both tenants with their weights
    for t, key in ((tp, "tp"), (dp, "dp")):
        stats = t.lane_stats()
        assert stats["cycles"] >= steps, (key, stats)
        line = t._runtime._world_status_line()
        assert f"tenant {key}" in line and "weight" in line, line

    # solo replay of the TP sequence (DP idle) is bit-identical:
    # co-tenancy never perturbed the numerics
    for i in range(steps):
        part = (A[:, rank * k:(rank + 1) * k]
                @ B[rank * k:(rank + 1) * k, :]) * (i + 1)
        out = tp.allreduce(part, average=False, name="tp.replay")
        assert (np.asarray(out) == results["tp"][i]).all(), i

    # the default world is untouched by tenant traffic
    out = hvd.allreduce(np.full(4, float(rank), np.float64),
                        average=False, name="tpdp.dflt")
    np.testing.assert_allclose(out, sum(range(size)))
    tp.shutdown()
    dp.shutdown()


def scenario_tenants_priority(hvd, rank, size):
    """3:1 weights must skew the contended cycle share toward the
    heavy tenant: when the heavy tenant finishes its fixed workload,
    the equal-sized light workload is measurably behind, and the
    light lane records real deferrals. Submissions ride a small async
    pipeline so both lanes stay backlogged."""
    import threading
    heavy = hvd.create_tenant("heavy", list(range(size)), weight=3.0)
    light = hvd.create_tenant("light", list(range(size)), weight=1.0)
    n, depth = 400, 4
    ssum = sum(range(1, size + 1))
    light_done_at_heavy_done = [None]

    def run(t, key):
        pend = []
        for i in range(n):
            pend.append(t.allreduce_async(
                np.full(16, float(rank + 1), np.float32),
                average=False, name=f"{key}.g{i % depth}"))
            if len(pend) >= depth:
                np.testing.assert_allclose(
                    t.synchronize(pend.pop(0)), ssum)
        while pend:
            np.testing.assert_allclose(t.synchronize(pend.pop(0)),
                                       ssum)
        if key == "h":
            light_done_at_heavy_done[0] = \
                light.lane_stats()["cycles"]

    threads = [threading.Thread(target=run, args=(t, k))
               for t, k in ((heavy, "h"), (light, "l"))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    h_cycles = heavy.lane_stats()["cycles"]
    l_at_h = light_done_at_heavy_done[0]
    # the heavy tenant held a strictly larger share of the contended
    # window (equal weights measure ~1.0 here; 3:1 measures ~1.5 on
    # this host since granted cycles still overlap — the quantitative
    # bar lives in collective_bench --multitenant; a loaded CI host
    # adds variance, so the gate here is the DIRECTION with margin
    # and a world-total deferral proof)
    assert l_at_h < 0.9 * h_cycles, (l_at_h, h_cycles)
    world_deferrals = float(np.asarray(light.allreduce(
        np.asarray([float(light.lane_stats()["deferrals"])],
                   np.float32),
        average=False, name="l.defer"))[0])
    assert world_deferrals > 0, light.lane_stats()
    heavy.shutdown()
    light.shutdown()


def scenario_tenants_quota(hvd, rank, size):
    """A cycles/sec quota defers the over-quota tenant — it crawls at
    the budget but every cycle completes exactly (deferred, never
    corrupted) while the unlimited co-tenant runs at full speed."""
    import threading
    import time as _time
    fast = hvd.create_tenant("fast", list(range(size)))
    capped = hvd.create_tenant("capped", list(range(size)),
                               quota_cycles_s=10.0)
    timing = {}

    def run(t, key, steps):
        t0 = _time.monotonic()
        _tenant_steps(t, rank, size, key, steps, numel=16)
        timing[key] = _time.monotonic() - t0

    threads = [threading.Thread(target=run, args=(fast, "f", 150)),
               threading.Thread(target=run, args=(capped, "c", 30))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    c = capped.lane_stats()
    # Deferral is observed per RANK; on a heavily loaded CI host one
    # rank's natural pace can fall under the quota (nothing for its
    # bucket to defer) — so assert on the WORLD total, with a
    # wall-time floor as the loaded-host fallback: 30 cycles at 10/s
    # minus the 1s burst bucket of 10 needs ~2s no matter what.
    world_deferrals = float(np.asarray(capped.allreduce(
        np.asarray([float(c["deferrals"])], np.float32),
        average=False, name="c.defer"))[0])
    assert world_deferrals > 0 or timing["c"] > 3.0, \
        (c, timing)
    assert timing["c"] > 1.4, timing
    # the unlimited tenant is not dragged to the capped tenant's
    # pace: compare PER-STEP pace, not total walls — the 5x larger
    # free workload racing the capped wall flakes on a loaded host
    # where raw step cost approaches the quota gap (brief fast
    # deferrals around the capped lane's refill instants are correct
    # weighted fairness, so deferral COUNTS are not compared)
    assert timing["f"] / 150 < (timing["c"] / 30) / 2, timing
    fast.shutdown()
    capped.shutdown()


def scenario_tenants_fault_isolation(hvd, rank, size):
    """SIGKILL of a rank inside tenant A ([0,1]) raises
    WorldAbortedError naming A's dead rank on A's survivor ONLY;
    tenant B ([2,3]) — disjoint ranks of the SAME launched fleet —
    trains to completion with exact results and never observes an
    abort."""
    import signal
    import time as _time
    from horovod_tpu.common.status import WorldAbortedError
    assert size == 4, "scenario expects 4 launched processes"
    ta = hvd.create_tenant("jobA", [0, 1])
    tb = hvd.create_tenant("jobB", [2, 3])
    if rank in (0, 1):
        assert ta is not None and tb is None
        assert ta.size == 2 and ta.rank == rank
        _tenant_steps(ta, ta.rank, 2, "a", 5, numel=16)
        if rank == 1:
            os.kill(os.getpid(), signal.SIGKILL)
        # survivor: drive tenant-A collectives until the fail-fast
        # abort surfaces, naming A's (tenant-local) rank 1
        t0 = _time.monotonic()
        i = 0
        while True:
            try:
                ta.allreduce(np.ones(16, np.float32), average=False,
                             name=f"a.post/{i}")
            except WorldAbortedError as e:
                assert e.origin_rank == 1, e
                break
            i += 1
            assert _time.monotonic() - t0 < 40.0, \
                "tenant A kept succeeding past its member's death"
        ta.shutdown()
        return
    # ranks 2, 3: tenant B must be completely unaffected — train
    # through the kill window and well past it
    assert tb is not None and ta is None
    assert tb.size == 2 and tb.rank == rank - 2
    for i in range(40):
        out = tb.allreduce(
            np.full(16, float(tb.rank + 1) * (i + 1), np.float32),
            average=False, name="b.g")
        np.testing.assert_allclose(out, 3.0 * (i + 1))
        _time.sleep(0.05)  # stretch across A's death + detection
    assert tb.alive, "tenant B's world must survive tenant A's abort"
    tb.shutdown()


def scenario_tenants_service(hvd, rank, size):
    """Service mode end to end on one launch: ranks 0-1 form a warm
    --service fleet (HOROVOD_TPU_SERVICE=1) that trains and publishes
    parameter snapshots; ranks 2-3 never join the fleet's world —
    they ATTACH as a 2-replica group, pull a snapshot through the
    broadcast fanout (gate → root → child), verify it, and DETACH.
    The fleet trains to completion without any re-rendezvous."""
    import time as _time
    assert size == 4, "scenario expects 4 launched processes"
    gate_port = int(os.environ["HOROVOD_TPU_SERVICE_PORT"])
    # The gate speaks the fleet's HMAC'd channel framing: an attaching
    # job must present the fleet's HOROVOD_SECRET_KEY (the service
    # plane shares the control plane's auth boundary — an unsecured
    # dialer is rejected at the first frame). The suite sometimes runs
    # with a secret inherited from the environment, so thread it.
    secret = os.environ.get("HOROVOD_SECRET_KEY", "").encode()
    if rank >= 2:
        # attach clients: no hvd.init() at all — a service job needs
        # only the gate endpoint (+ secret). Generous deadlines: under
        # a loaded CI host, interpreter+numpy startup alone can eat
        # tens of seconds before this line runs.
        from horovod_tpu.common import tenancy
        print(f"[client {rank}] dialing gate 127.0.0.1:{gate_port}",
              flush=True)
        rep = tenancy.attach("127.0.0.1", gate_port, "evaljob",
                             replica=rank - 2, group=2, timeout=90.0,
                             secret=secret)
        print(f"[client {rank}] lease {rep.lease} members "
              f"{rep.members}", flush=True)
        assert len(rep.members) == 2
        version, params = rep.fetch_snapshot(min_version=1,
                                             timeout=60.0)
        print(f"[client {rank}] snapshot v{version}", flush=True)
        assert version >= 1
        np.testing.assert_array_equal(
            params["w"], np.arange(16, dtype=np.float32) * version)
        assert int(params["step"][0]) == version * 10
        rep.detach()
        return
    # fleet ranks 0-1: a 2-rank world on the env endpoint
    hvd.init(comm=(rank, 2))
    from horovod_tpu.common import tenancy
    gate = tenancy.service_gate()
    if rank == 0:
        assert gate is not None and gate.port == gate_port
        print(f"[fleet 0] gate up on {gate.port} pid {os.getpid()}",
              flush=True)
    ssum = 3.0  # ranks contribute 1.0 and 2.0
    for step in range(1, 61):
        out = hvd.allreduce(np.full(8, float(rank + 1), np.float32),
                            average=False, name="fleet.g")
        np.testing.assert_allclose(out, ssum)
        if rank == 0 and step % 10 == 0:
            tenancy.publish_snapshot(
                {"w": np.arange(16, dtype=np.float32) * (step // 10),
                 "step": np.asarray([step], np.int64)},
                version=step // 10)
        _time.sleep(0.02)
    if rank == 0:
        # the fleet never re-rendezvoused: wait for both replicas to
        # have come AND gone (the gate runs on daemon threads beside
        # the world — no collective is needed to serve them, which is
        # the point). The window covers loaded-host client startup.
        deadline = _time.monotonic() + 120.0
        while _time.monotonic() < deadline:
            s = gate.stats()
            if s["attaches"] >= 2 and s["detaches"] >= 2:
                break
            _time.sleep(0.1)
        s = gate.stats()
        assert s["attaches"] >= 2 and s["detaches"] >= 2, s
        assert s["groups"] == {}, s
    # a final world collective proves the fleet world is still whole
    out = hvd.allreduce(np.full(4, float(rank + 1), np.float32),
                        average=False, name="fleet.final")
    np.testing.assert_allclose(out, ssum)


scenario_tenants_service.no_auto_init = True


# -- PR 16: batched reactor, native int8 codec, chunked relay ----------

def scenario_abort_sigkill_batched_gather(hvd, rank, size):
    """SIGKILL rank 1 while the coordinator sits in the BATCHED
    reactor gather (socket star, shm/ring off by the wrapper): the
    io_uring/poll batched submission must honor the same recv
    deadlines and heartbeat absorption as the sequential loop —
    survivors raise WorldAbortedError naming rank 1 within the
    detection deadline instead of hanging in the kernel."""
    deadline = float(os.environ["HOROVOD_HEARTBEAT_TIMEOUT"]) + 12.0
    _await_world_abort(hvd, rank, 1, deadline, "bg.sk")


def scenario_abort_sever_batched_gather(hvd, rank, size):
    """Fault-injected link severance mid-batched-gather: rank 1's
    upward channel dies abruptly (process alive), the coordinator's
    batched submission sees the EOF among its completions and must
    blame rank 1; the severed rank finds its own channel closed."""
    from horovod_tpu.common.status import HorovodInternalError

    victim = 1
    deadline = float(os.environ["HOROVOD_HEARTBEAT_TIMEOUT"]) + 12.0
    if rank == victim:
        try:
            while True:
                hvd.allreduce(np.ones(64, np.float32), average=False,
                              name="bg.sv")
        except HorovodInternalError:
            pass
        hvd.shutdown()
        return
    _await_world_abort(hvd, rank, victim, deadline, "bg.sv")


def scenario_reactor_exact(hvd, rank, size):
    """Reactor-knob sweep driver: a mixed-collective schedule
    (allreduce, allgather, reducescatter, broadcast, alltoall) whose
    rank-0 outputs land in HVD_REACTOR_OUT for the wrapper to
    byte-compare across worlds — HOROVOD_TPU_REACTOR is recv
    discipline only, so all-on, all-off and HETEROGENEOUS worlds must
    put the same bytes on the wire and compute identical results.
    With HVD_EXPECT_REACTOR=1 the coordinator additionally proves the
    batched path actually engaged (the A/B is not vacuous)."""
    rng = np.random.RandomState(7000 + rank)
    outs = []
    for step in range(6):
        x = rng.randn(1024).astype(np.float32)
        outs.append(np.asarray(
            hvd.allreduce(x, average=False, name=f"rx.{step}")))
    g = hvd.allgather(
        np.arange(6, dtype=np.float32).reshape(3, 2) + 100 * rank,
        name="rx.ag")
    outs.append(np.asarray(g).reshape(-1))
    rs = hvd.reducescatter(
        np.arange(size * 4, dtype=np.float32) * (rank + 1), name="rx.rs")
    outs.append(np.asarray(rs).reshape(-1))
    b = hvd.broadcast(np.full(33, float(rank), np.float32),
                      root_rank=size - 1, name="rx.bc")
    outs.append(np.asarray(b))
    a2a = hvd.alltoall(
        np.arange(size * 2, dtype=np.float32) + 100 * rank,
        name="rx.a2a")
    outs.append(np.asarray(a2a).reshape(-1))
    # pin correctness locally too, not just cross-world identity
    np.testing.assert_allclose(
        outs[-1], np.concatenate(
            [np.arange(rank * 2, (rank + 1) * 2) + 100 * src
             for src in range(size)]).astype(np.float32))
    np.testing.assert_allclose(b, float(size - 1))
    out_path = os.environ.get("HVD_REACTOR_OUT")
    if rank == 0 and out_path:
        np.save(out_path, np.concatenate([o.reshape(-1) for o in outs]))
    if os.environ.get("HVD_EXPECT_REACTOR") == "1" and rank == 0:
        from horovod_tpu import native as _nat
        if _nat.get() is not None:
            assert _metric_value(hvd, "hvd_reactor_batch_size") > 0, \
                "batched reactor never engaged on the coordinator"


def scenario_int8_codec_parity(hvd, rank, size):
    """Native-codec convergence parity driver: an int8+error-feedback
    steady loop (same fused batch every step, so the residual chain
    matters) whose outputs land in HVD_REACTOR_OUT. The wrapper runs
    this world twice — native codec vs HOROVOD_NATIVE=0 numpy codec —
    and compares byte-for-byte: hvd_quant8/hvd_dequant8 are
    BIT-IDENTICAL to the numpy reference, so swapping them changes
    nothing about training."""
    rng = np.random.RandomState(8000 + rank)
    outs = []
    for step in range(10):
        xs = [rng.randn(777).astype(np.float32),
              rng.randn(333).astype(np.float32)]
        got = hvd.grouped_allreduce(xs, average=False, name="i8")
        outs.extend(np.asarray(o) for o in got)
    # reducescatter rides the int8 star verdict too (PR 16 extension)
    rs = hvd.reducescatter(
        rng.randn(size * 8).astype(np.float32), name="i8.rs")
    outs.append(np.asarray(rs).reshape(-1))
    out_path = os.environ.get("HVD_REACTOR_OUT")
    if rank == 0 and out_path:
        np.save(out_path, np.concatenate([o.reshape(-1) for o in outs]))


def scenario_ici_steady(hvd, rank, size):
    """ICI-native fused-psum steady cycle end to end (the wrapper arms
    HOROVOD_TPU_ICI=1 over a forced multi-device host mesh — conftest
    already exports ``--xla_force_host_platform_device_count=8`` to
    every spawned world): the steady grouped-allreduce loop must (a)
    return correct sums, (b) ride the PRE-COMPILED fused-psum
    executable — ici_cycles advancing every steady step while
    ici_compiles stays FLAT across 25 replays (100% reuse, over the
    >=95% acceptance bar), (c) keep hvd_data_copies_total delta 0 on
    the Python side of the mesh leg, and (d) prove the coordinator
    stamped ALG_ICI (ici_cycles only tick on an ALG_ICI verdict, so
    their advance IS the stamp).  With HVD_ICI_EXPECT=0 the same body
    asserts the world-consistent DEGRADE instead (heterogeneous
    worlds and the all-socket replay: zero ici cycles anywhere); the
    wrapper byte-compares both worlds' saved outputs for the
    bit-exactness leg."""
    from horovod_tpu.common import basics as _b

    expect_ici = os.environ.get("HVD_ICI_EXPECT", "1") == "1"
    rng = np.random.RandomState(7100 + rank)
    xs = [rng.randn(512 + 128 * i).astype(np.float32) for i in range(4)]
    # every rank reconstructs the world sum from the seeds, so
    # correctness is pinned locally even over random payloads
    want = [np.zeros_like(x) for x in xs]
    for r in range(size):
        rr = np.random.RandomState(7100 + r)
        for i in range(4):
            want[i] = want[i] + rr.randn(512 + 128 * i).astype(
                np.float32)

    def step():
        hs = hvd.grouped_allreduce_async(xs, average=False, name="ici")
        return [np.asarray(hvd.synchronize(h)) for h in hs]

    for _ in range(5):
        res = step()
    hvd.barrier(name="ici.bar")
    rt = _b.runtime()
    s0 = rt.negotiation_cache_stats()
    c0 = _metric_value(hvd, "hvd_data_copies_total")
    for _ in range(25):
        res = step()
    s1 = rt.negotiation_cache_stats()
    c1 = _metric_value(hvd, "hvd_data_copies_total")
    # bf16 wire: contributions round to 8 mantissa bits before the sum
    tol = (0.02 * max(float(np.abs(w).max()) for w in want)
           if os.environ.get("HOROVOD_COMPRESSION") else 1e-5)
    for r, w in zip(res, want):
        np.testing.assert_allclose(r, w, atol=tol)
    assert s1["spec_cycles"] > s0["spec_cycles"], (rank, s0, s1)
    if expect_ici:
        assert s1["ici_cycles"] - s0["ici_cycles"] >= 20, (rank, s0, s1)
        # steady cycles ride the cached executable: compile count flat
        assert s1["ici_compiles"] == s0["ici_compiles"], (rank, s0, s1)
        assert _metric_value(hvd, "hvd_ici_cycles_total") > 0, rank
        assert _metric_value(
            hvd, 'hvd_backend_bytes_total{backend="ici_mesh"}') > 0, \
            rank
        assert c1 - c0 == 0, (rank, c0, c1)
    else:
        # degrade must be WORLD-consistent: no rank ever packs on ICI
        assert s1["ici_cycles"] == 0, (rank, s1)
        assert _metric_value(hvd, "hvd_ici_cycles_total") == 0, rank
    out_path = os.environ.get("HVD_ICI_OUT")
    if rank == 0 and out_path:
        np.save(out_path, np.concatenate([o.reshape(-1) for o in res]))
    _assert_cache_coherent(hvd, rank, size, "ici.fp")


def scenario_abort_sigkill_ici_steady(hvd, rank, size):
    """SIGKILL a rank squarely mid-ICI-fused-psum steady state (fault
    spec fires at an op index reached deep in ALG_ICI steady cycling):
    the mesh leg must not mask the PR 2 fail-fast invariant — every
    survivor raises WorldAbortedError naming the dead rank within the
    heartbeat deadline, and its stats prove the kill really landed in
    ICI steady state."""
    import time
    from horovod_tpu.common.status import WorldAbortedError

    victim = 1
    deadline = float(os.environ["HOROVOD_HEARTBEAT_TIMEOUT"]) + 12.0
    # f32: the mesh leg declines f64 without jax_enable_x64, and this
    # scenario must die with the plane ENGAGED
    x = np.full(1024, float(rank + 1), np.float32)
    t0 = time.monotonic()
    aborted = None
    while True:
        try:
            hvd.allreduce(x, average=False, name="ik.steady")
        except WorldAbortedError as e:
            aborted = e
            break
        assert time.monotonic() - t0 < deadline, (
            f"rank {rank}: collectives kept succeeding {deadline}s "
            f"after the fault")
    assert aborted.origin_rank == victim, (rank, str(aborted))
    assert f"rank {victim}" in str(aborted), str(aborted)
    stats = _cache_runtime_stats(hvd)
    # the kill landed with the ICI plane engaged and cycling
    assert stats["ici_cycles"] >= 5, stats
    try:
        hvd.allreduce(x, average=False, name="ik.post")
        raise AssertionError("enqueue after world abort must fail")
    except WorldAbortedError as e:
        assert e.origin_rank == victim, str(e)
    hvd.shutdown()


def main():
    scenario, rank, size, port = (sys.argv[1], int(sys.argv[2]),
                                  int(sys.argv[3]), int(sys.argv[4]))
    # Hard in-process deadline (set by run_scenario slightly under its
    # subprocess timeout): a deadlocked rank dumps every thread's stack
    # and exits nonzero, so a regression that reintroduces a hang fails
    # fast WITH a diagnosis instead of eating the tier-1 time budget
    # and reporting only "timed out".
    deadline = float(os.environ.get("HOROVOD_TEST_DEADLINE", "0"))
    if deadline > 0:
        import faulthandler
        faulthandler.dump_traceback_later(deadline, exit=True)
    os.environ["HOROVOD_RANK"] = str(rank)
    os.environ["HOROVOD_SIZE"] = str(size)
    os.environ["HOROVOD_CONTROLLER_ADDR"] = "127.0.0.1"
    os.environ["HOROVOD_CONTROLLER_PORT"] = str(port)
    os.environ.setdefault("HOROVOD_CYCLE_TIME", "1")
    import horovod_tpu as hvd
    fn = globals()[f"scenario_{scenario}"]
    if not getattr(fn, "no_auto_init", False):
        hvd.init()
    try:
        fn(hvd, rank, size)
    finally:
        hvd.shutdown()


if __name__ == "__main__":
    main()
