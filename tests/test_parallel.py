"""Parallelism extensions: ring attention exactness, TP sharding rules,
and the composed dp x tp (x sp) Trainer on the 8-device CPU mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from horovod_tpu import spmd
from horovod_tpu.models.transformer import (
    TransformerConfig, TransformerLM, causal_attention,
)
from horovod_tpu.compat import jaxshim
from horovod_tpu.parallel import (
    Trainer, TrainerConfig, infer_sharding, make_ring_attention,
    ring_attention, transformer_tp_rules,
)


def test_ring_attention_matches_reference():
    """Sequence sharded over 4 devices must reproduce single-device
    causal attention to fp32 tolerance."""
    mesh = spmd.create_mesh({"seq": 4}, devices=jax.devices()[:4])
    b, s, h, d = 2, 16, 2, 8
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)

    expected = causal_attention(q, k, v)

    f = jax.jit(jaxshim.shard_map(
        lambda q, k, v: ring_attention(q, k, v, axis="seq"),
        mesh=mesh, in_specs=(P(None, "seq"),) * 3,
        out_specs=P(None, "seq")))
    out = f(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               atol=2e-5)


def test_ring_attention_single_shard_degenerates():
    mesh = spmd.create_mesh({"seq": 1}, devices=jax.devices()[:1])
    b, s, h, d = 1, 8, 1, 4
    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    f = jax.jit(jaxshim.shard_map(
        lambda q, k, v: ring_attention(q, k, v, axis="seq"),
        mesh=mesh, in_specs=(P(),) * 3, out_specs=P()))
    np.testing.assert_allclose(np.asarray(f(q, k, v)),
                               np.asarray(causal_attention(q, k, v)),
                               atol=2e-5)


def test_tp_rules_match_expected_paths():
    cfg = TransformerConfig(vocab_size=64, num_layers=1, num_heads=4,
                            head_dim=4, dtype=jnp.float32)
    model = TransformerLM(cfg)
    tokens = jnp.zeros((1, 8), jnp.int32)
    params = model.init(jax.random.key(0), tokens)
    mesh = spmd.create_mesh({"data": 4, "model": 2})
    shardings = infer_sharding(params, transformer_tp_rules("model"), mesh)
    flat = {"/".join(str(getattr(k, "key", k)) for k in path): s
            for path, s in
            jax.tree_util.tree_flatten_with_path(shardings)[0]}
    qk = [k for k in flat if k.endswith("attn/q/kernel")][0]
    assert flat[qk].spec == P(None, "model", None)
    up = [k for k in flat if k.endswith("mlp/up/kernel")][0]
    assert flat[up].spec == P(None, "model")
    ln = [k for k in flat if "ln1/scale" in k][0]
    assert flat[ln].spec == P()


def _tiny_cfg(attention_fn=None):
    return TransformerConfig(vocab_size=64, num_layers=2, num_heads=4,
                             head_dim=8, max_seq_len=16,
                             dtype=jnp.float32, attention_fn=attention_fn)


def test_trainer_dp_tp_step_runs_and_improves():
    import optax
    mesh = spmd.create_mesh({"data": 4, "model": 2})
    model = TransformerLM(_tiny_cfg())
    trainer = Trainer(model, mesh, optax.adam(1e-2),
                      TrainerConfig(data_axis="data", model_axis="model"))
    tokens = np.tile(np.arange(16, dtype=np.int32)[None], (8, 1))
    batch = {"tokens": tokens}
    state = trainer.init(jax.random.key(0), batch)
    losses = []
    for _ in range(5):
        state, loss = trainer.train_step(state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_trainer_dp_tp_sp_with_ring_attention():
    import optax
    mesh = spmd.create_mesh({"data": 2, "seq": 2, "model": 2})
    attn = make_ring_attention(mesh, data_axis="data", seq_axis="seq",
                               model_axis="model")
    model = TransformerLM(_tiny_cfg(attention_fn=attn))
    trainer = Trainer(model, mesh, optax.sgd(1e-2),
                      TrainerConfig(data_axis="data", model_axis="model",
                                    seq_axis="seq"))
    tokens = np.tile(np.arange(16, dtype=np.int32)[None], (4, 1))
    batch = {"tokens": tokens}
    state = trainer.init(jax.random.key(0), batch)
    state, loss0 = trainer.train_step(state, batch)
    state, loss1 = trainer.train_step(state, batch)
    assert np.isfinite(loss0) and np.isfinite(loss1)
    assert float(loss1) < float(loss0)


def test_sp_matches_dense_attention_loss():
    """Loss with ring attention == loss with dense attention."""
    import optax
    mesh = spmd.create_mesh({"data": 2, "seq": 4})
    attn = make_ring_attention(mesh, data_axis="data", seq_axis="seq",
                               model_axis=None)
    tokens = np.tile(np.arange(16, dtype=np.int32)[None], (4, 1))
    batch = {"tokens": tokens}

    dense = Trainer(TransformerLM(_tiny_cfg()), mesh, optax.sgd(1e-2),
                    TrainerConfig(model_axis=None, seq_axis="seq"))
    ringy = Trainer(TransformerLM(_tiny_cfg(attention_fn=attn)), mesh,
                    optax.sgd(1e-2),
                    TrainerConfig(model_axis=None, seq_axis="seq"))
    s0 = dense.init(jax.random.key(7), batch)
    s1 = ringy.init(jax.random.key(7), batch)
    _, l0 = dense.train_step(s0, batch)
    _, l1 = ringy.train_step(s1, batch)
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-4)


# ---------------------------------------------------------------------------
# pallas flash attention (interpret mode on CPU)
# ---------------------------------------------------------------------------

def test_flash_attention_matches_dense():
    from horovod_tpu.parallel.flash_attention import flash_attention
    rng = np.random.RandomState(3)
    b, s, h, d = 2, 128, 2, 32
    q = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    out = flash_attention(q, k, v, causal=True, block_q=32, block_k=32,
                          interpret=True)
    ref = causal_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5)


def test_flash_attention_offsets_match_ring_semantics():
    """With q_offset/k_offset the kernel must reproduce the masked
    cross-block attention ring attention needs: a kv block entirely in
    the past attends fully; entirely in the future contributes zero."""
    from horovod_tpu.parallel.flash_attention import flash_attention
    rng = np.random.RandomState(4)
    b, s, h, d = 1, 64, 1, 16
    q = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)

    # q block at global [64,128), kv block at [0,64): fully visible
    out = flash_attention(q, k, v, causal=True, q_offset=64, k_offset=0,
                          block_q=32, block_k=32, interpret=True)
    # equivalent dense: no mask at all (all k_pos < q_pos)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(d)
    probs = jax.nn.softmax(logits, axis=-1)
    ref = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5)

    # kv block fully in the future: all masked -> zeros (guarded denom)
    out = flash_attention(q, k, v, causal=True, q_offset=0, k_offset=64,
                          block_q=32, block_k=32, interpret=True)
    np.testing.assert_allclose(np.asarray(out), 0.0, atol=1e-6)


def test_flash_attention_indivisible_falls_back():
    from horovod_tpu.parallel.flash_attention import flash_attention
    rng = np.random.RandomState(5)
    b, s, h, d = 1, 50, 1, 8  # 50 not divisible by any pow2 block
    q = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    out = flash_attention(q, k, v, causal=True, block_q=32, block_k=32,
                          interpret=True)
    ref = causal_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5)


def test_ring_attention_flash_path_matches_dense():
    """Forced flash path (pallas interpret on CPU): forward and grad
    must match dense causal attention exactly."""
    from functools import partial
    mesh = spmd.create_mesh({"seq": 4}, devices=jax.devices()[:4])
    b, s, h, d = 1, 64, 2, 16
    rng = np.random.RandomState(7)
    q = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    f = jax.jit(jaxshim.shard_map(
        lambda q, k, v: ring_attention(q, k, v, axis="seq",
                                       use_flash=True),
        mesh=mesh, in_specs=(P(None, "seq"),) * 3,
        out_specs=P(None, "seq")))
    np.testing.assert_allclose(np.asarray(f(q, k, v)),
                               np.asarray(causal_attention(q, k, v)),
                               atol=2e-5)
    g1 = jax.grad(lambda q, k, v: (f(q, k, v) ** 2).sum(),
                  argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(
        lambda q, k, v: (causal_attention(q, k, v) ** 2).sum(),
        argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   atol=1e-4)


def test_flash_attention_grad_matches_dense():
    """The pallas backward kernels (dq / dk+dv) must reproduce dense
    causal-attention gradients — no O(S²) recompute fallback anymore."""
    from horovod_tpu.parallel.flash_attention import flash_attention
    rng = np.random.RandomState(11)
    b, s, h, d = 2, 64, 2, 16
    q = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)

    def loss_flash(q, k, v):
        out = flash_attention(q, k, v, causal=True, block_q=32,
                              block_k=32, interpret=True)
        return (out ** 2).sum()

    def loss_dense(q, k, v):
        return (causal_attention(q, k, v) ** 2).sum()

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   atol=1e-4)


def test_flash_attention_grad_noncausal_and_offsets():
    from horovod_tpu.parallel.flash_attention import flash_attention
    rng = np.random.RandomState(12)
    b, s, h, d = 1, 64, 1, 8
    q = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)

    def dense_nc(q, k, v):
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(d)
        probs = jax.nn.softmax(logits, axis=-1)
        return jnp.einsum("bhqk,bkhd->bqhd", probs, v)

    # non-causal
    g1 = jax.grad(lambda *a: (flash_attention(
        *a, causal=False, block_q=32, block_k=32,
        interpret=True) ** 2).sum(), argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(lambda *a: (dense_nc(*a) ** 2).sum(),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   atol=1e-4)

    # causal with a fully-past kv block (ring step shape): same as
    # non-causal dense
    g1 = jax.grad(lambda *a: (flash_attention(
        *a, causal=True, q_offset=64, k_offset=0, block_q=32,
        block_k=32, interpret=True) ** 2).sum(),
        argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   atol=1e-4)

    # fully-future kv block: zero output -> zero grads, no NaN from
    # dead rows (l == 0)
    g1 = jax.grad(lambda *a: (flash_attention(
        *a, causal=True, q_offset=0, k_offset=64, block_q=32,
        block_k=32, interpret=True) ** 2).sum(),
        argnums=(0, 1, 2))(q, k, v)
    for a in g1:
        assert np.all(np.isfinite(np.asarray(a)))
        np.testing.assert_allclose(np.asarray(a), 0.0, atol=1e-6)


def test_ring_attention_flash_noncausal():
    """use_flash=True with causal=False must compute NON-causal
    attention (was: silently causal)."""
    mesh = spmd.create_mesh({"seq": 4}, devices=jax.devices()[:4])
    b, s, h, d = 1, 64, 1, 8
    rng = np.random.RandomState(13)
    q = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    f = jax.jit(jaxshim.shard_map(
        lambda q, k, v: ring_attention(q, k, v, causal=False,
                                       axis="seq", use_flash=True),
        mesh=mesh, in_specs=(P(None, "seq"),) * 3,
        out_specs=P(None, "seq")))
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(d)
    ref = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(logits, -1), v)
    np.testing.assert_allclose(np.asarray(f(q, k, v)), np.asarray(ref),
                               atol=2e-5)


def test_flash_attention_stats_values():
    from horovod_tpu.parallel.flash_attention import flash_attention_stats
    rng = np.random.RandomState(8)
    b, s, h, d = 1, 64, 1, 8
    q = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    o, m, l = flash_attention_stats(q, k, v, causal=True, block_q=32,
                                    block_k=32, interpret=True)
    logits = np.einsum("bqhd,bkhd->bhqk", np.asarray(q),
                       np.asarray(k)) / np.sqrt(d)
    mask = np.tril(np.ones((s, s), bool))
    logits = np.where(mask[None, None], logits, -1e30)
    m_ref = logits.max(-1)
    l_ref = np.exp(logits - m_ref[..., None]).sum(-1)
    np.testing.assert_allclose(np.asarray(m), m_ref, atol=1e-5)
    np.testing.assert_allclose(np.asarray(l), l_ref, rtol=1e-5)


# ---------------------------------------------------------------------------
# Expert parallelism (MoE)
# ---------------------------------------------------------------------------

def test_moe_matches_per_token_reference():
    """MoEMLP's dispatch/combine einsums == routing each token through
    its argmax expert directly (capacity ample, nothing dropped)."""
    from horovod_tpu.models.transformer import MoEMLP, TransformerConfig

    cfg = TransformerConfig(vocab_size=64, num_layers=1, num_heads=2,
                            head_dim=4, mlp_ratio=2, dtype=jnp.float32,
                            num_experts=4, expert_capacity_factor=4.0)
    layer = MoEMLP(cfg)
    x = jax.random.normal(jax.random.key(0), (2, 8, cfg.embed_dim),
                          jnp.float32)
    variables = layer.init(jax.random.key(1), x)
    y = layer.apply(variables, x)

    p = variables["params"]
    wr = np.asarray(p["router"]["kernel"], np.float64)
    w1 = np.asarray(p["w1"], np.float64)
    w2 = np.asarray(p["w2"], np.float64)
    xt = np.asarray(x, np.float64).reshape(-1, cfg.embed_dim)
    logits = xt @ wr
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    idx = probs.argmax(-1)
    ref = np.zeros_like(xt)
    gelu = lambda v: 0.5 * v * (1 + np.tanh(
        np.sqrt(2 / np.pi) * (v + 0.044715 * v ** 3)))
    for n in range(xt.shape[0]):
        e = idx[n]
        ref[n] = probs[n, e] * (gelu(xt[n] @ w1[e]) @ w2[e])
    np.testing.assert_allclose(np.asarray(y).reshape(-1, cfg.embed_dim),
                               ref, rtol=2e-4, atol=2e-5)


def test_moe_capacity_drops_overflow_tokens():
    """With capacity 1 and every token routed to one expert, only the
    first token per expert survives; the rest combine to zero."""
    from horovod_tpu.models.transformer import MoEMLP, TransformerConfig

    cfg = TransformerConfig(vocab_size=64, num_layers=1, num_heads=2,
                            head_dim=4, mlp_ratio=2, dtype=jnp.float32,
                            num_experts=2,
                            expert_capacity_factor=2 / 8.0)  # C = 1
    layer = MoEMLP(cfg)
    x = jnp.tile(jax.random.normal(jax.random.key(0),
                                   (1, 1, cfg.embed_dim)), (1, 4, 1))
    variables = layer.init(jax.random.key(1), x)
    y = np.asarray(layer.apply(variables, x))[0]
    # identical tokens -> same expert; capacity 1 keeps only token 0
    assert np.any(y[0] != 0.0)
    np.testing.assert_allclose(y[1:], 0.0)


def test_trainer_dp_tp_ep_step_runs_and_shards_experts():
    """dp x tp x ep on the 8-device CPU mesh: expert weights sharded
    over the expert axis (composed with the per-expert Megatron split),
    the step runs, and the loss improves."""
    import optax
    from horovod_tpu.models.transformer import TransformerConfig

    mesh = spmd.create_mesh({"data": 2, "expert": 2, "model": 2})
    cfg = TransformerConfig(vocab_size=64, num_layers=2, num_heads=4,
                            head_dim=8, max_seq_len=16,
                            dtype=jnp.float32, num_experts=2,
                            moe_every=2)
    trainer = Trainer(TransformerLM(cfg), mesh, optax.adam(1e-2),
                      TrainerConfig(data_axis="data", model_axis="model",
                                    expert_axis="expert"))
    tokens = np.tile(np.arange(16, dtype=np.int32)[None], (8, 1))
    batch = {"tokens": tokens}
    state = trainer.init(jax.random.key(0), batch)

    moe_params = state["params"]["params"]["block_1"]["moe"]
    w1_sharding = moe_params["w1"].sharding
    assert w1_sharding.spec == P("expert", None, "model"), w1_sharding
    router_sharding = moe_params["router"]["kernel"].sharding
    assert router_sharding.spec == P(), router_sharding

    losses = []
    for _ in range(5):
        state, loss = trainer.train_step(state, batch)
        losses.append(float(loss))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]


def test_moe_aux_loss_sowed():
    from horovod_tpu.models.transformer import (
        TransformerConfig, TransformerLM, moe_aux_loss,
    )
    cfg = TransformerConfig(vocab_size=64, num_layers=2, num_heads=2,
                            head_dim=4, dtype=jnp.float32,
                            num_experts=2, moe_every=2)
    model = TransformerLM(cfg)
    tokens = jnp.zeros((2, 8), jnp.int32)
    variables = model.init(jax.random.key(0), tokens)
    _, inter = model.apply(variables, tokens,
                           mutable=["intermediates"])
    aux = moe_aux_loss(inter["intermediates"])
    # perfectly balanced routing gives aux == 1.0; anything routed
    # gives a finite positive value >= 1 for top-1 switch gating
    assert float(aux) >= 1.0 - 1e-3


def test_ep_without_tp_still_shards_experts():
    """expert_axis without model_axis must still emit expert rules
    (PartitionSpec treats the absent model split as replicated)."""
    import optax
    from horovod_tpu.models.transformer import TransformerConfig

    mesh = spmd.create_mesh({"data": 2, "expert": 4})
    cfg = TransformerConfig(vocab_size=64, num_layers=2, num_heads=2,
                            head_dim=4, dtype=jnp.float32,
                            num_experts=4, moe_every=2)
    trainer = Trainer(TransformerLM(cfg), mesh, optax.sgd(1e-2),
                      TrainerConfig(data_axis="data", model_axis=None,
                                    expert_axis="expert"))
    batch = {"tokens": np.tile(np.arange(8, dtype=np.int32)[None],
                               (4, 1))}
    state = trainer.init(jax.random.key(0), batch)
    w1 = state["params"]["params"]["block_1"]["moe"]["w1"]
    assert w1.sharding.spec == P("expert", None, None), w1.sharding
    state, loss = trainer.train_step(state, batch)
    assert np.isfinite(float(loss))


def test_indivisible_expert_axis_fails_with_clear_error():
    """An expert axis larger than num_experts must fail at init with an
    actionable message, not a deep device_put error."""
    import optax
    import pytest as _pytest
    from horovod_tpu.models.transformer import TransformerConfig

    mesh = spmd.create_mesh({"data": 1, "expert": 8})
    cfg = TransformerConfig(vocab_size=64, num_layers=2, num_heads=2,
                            head_dim=4, dtype=jnp.float32,
                            num_experts=2, moe_every=2)
    trainer = Trainer(TransformerLM(cfg), mesh, optax.sgd(1e-2),
                      TrainerConfig(data_axis="data", model_axis=None,
                                    expert_axis="expert"))
    batch = {"tokens": np.zeros((1, 8), np.int32)}
    with _pytest.raises(ValueError, match="num_experts"):
        trainer.init(jax.random.key(0), batch)


# ---------------------------------------------------------------------------
# Pipeline parallelism (GPipe over a mesh axis)
# ---------------------------------------------------------------------------

def _pp_block(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def _pp_setup(n_stages, d=8):
    rng = np.random.RandomState(0)
    stacked = {
        "w": jnp.asarray(rng.randn(n_stages, d, d) * 0.5, jnp.float32),
        "b": jnp.asarray(rng.randn(n_stages, d) * 0.1, jnp.float32),
    }
    x = jnp.asarray(rng.randn(8, d), jnp.float32)
    return stacked, x


def _pp_sequential(stacked, x):
    for s in range(stacked["w"].shape[0]):
        x = _pp_block({"w": stacked["w"][s], "b": stacked["b"][s]}, x)
    return x


@pytest.mark.parametrize("num_microbatches", [2, 4, 8])
def test_pipeline_matches_sequential(num_microbatches):
    """4 pipeline stages over 4 devices == running the 4 blocks
    sequentially, for any microbatch count."""
    from horovod_tpu.parallel import make_pipeline_apply
    mesh = spmd.create_mesh({"stage": 4}, devices=jax.devices()[:4])
    stacked, x = _pp_setup(4)
    run = make_pipeline_apply(mesh, _pp_block,
                              num_microbatches=num_microbatches)
    out = run(stacked, x)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_pp_sequential(stacked, x)),
                               atol=1e-5)


def test_pipeline_gradients_match_sequential():
    """Autodiff through the scan + ppermute schedule reproduces the
    sequential gradients (the backward schedule comes for free)."""
    from horovod_tpu.parallel import make_pipeline_apply
    mesh = spmd.create_mesh({"stage": 4}, devices=jax.devices()[:4])
    stacked, x = _pp_setup(4)

    run = make_pipeline_apply(mesh, _pp_block, num_microbatches=4)

    def pipe_loss(p):
        return jnp.mean(run(p, x) ** 2)

    def seq_loss(p):
        return jnp.mean(_pp_sequential(p, x) ** 2)

    gp = jax.grad(pipe_loss)(stacked)
    gs = jax.grad(seq_loss)(stacked)
    np.testing.assert_allclose(np.asarray(gp["w"]), np.asarray(gs["w"]),
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(gp["b"]), np.asarray(gs["b"]),
                               atol=1e-5)


def test_pipeline_transformer_blocks():
    """Pipeline the transformer's homogeneous block tower: 2 stages x
    identical Block params == sequential block application."""
    from horovod_tpu.parallel import make_pipeline_apply
    from horovod_tpu.models.transformer import Block

    cfg = _tiny_cfg()
    mesh = spmd.create_mesh({"stage": 2}, devices=jax.devices()[:2])
    block = Block(cfg)
    x = jnp.asarray(np.random.RandomState(1).randn(4, 16, cfg.embed_dim),
                    jnp.float32)
    positions = jnp.broadcast_to(jnp.arange(16, dtype=jnp.int32)[None],
                                 (4, 16))
    p0 = block.init(jax.random.key(0), x, positions)["params"]
    p1 = block.init(jax.random.key(1), x, positions)["params"]
    stacked = jax.tree_util.tree_map(
        lambda a, b: jnp.stack([a, b]), p0, p1)

    def block_fn(params, h):
        # positions derived per microbatch (batch-size agnostic)
        pos = jnp.broadcast_to(
            jnp.arange(h.shape[1], dtype=jnp.int32)[None], h.shape[:2])
        return block.apply({"params": params}, h, pos)

    run = make_pipeline_apply(mesh, block_fn, num_microbatches=2)
    out = run(stacked, x)
    ref = block_fn(p1, block_fn(p0, x))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5)


def test_pipeline_composes_with_data_parallelism():
    """dp x pp on the 8-device mesh (data=2, stage=4): forward and
    gradients match the sequential single-device reference; the
    gradient all-reduce over the data axis comes from shard_map's
    transpose, no manual psum."""
    from horovod_tpu.parallel import make_pipeline_apply
    mesh = spmd.create_mesh({"data": 2, "stage": 4})
    stacked, x = _pp_setup(4)

    run = make_pipeline_apply(mesh, _pp_block, num_microbatches=2,
                              data_axis="data")
    np.testing.assert_allclose(np.asarray(run(stacked, x)),
                               np.asarray(_pp_sequential(stacked, x)),
                               atol=1e-5)

    gp = jax.grad(lambda p: jnp.mean(run(p, x) ** 2))(stacked)
    gs = jax.grad(lambda p: jnp.mean(_pp_sequential(p, x) ** 2))(stacked)
    np.testing.assert_allclose(np.asarray(gp["w"]), np.asarray(gs["w"]),
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(gp["b"]), np.asarray(gs["b"]),
                               atol=1e-5)


def test_moe_top2_matches_per_token_reference():
    """Top-2 gating: each token's output is the gate-weighted sum of
    its two best experts' FFNs with gates renormalized over the pair
    (capacity ample, nothing dropped)."""
    from horovod_tpu.models.transformer import MoEMLP, TransformerConfig

    cfg = TransformerConfig(vocab_size=64, num_layers=1, num_heads=2,
                            head_dim=4, mlp_ratio=2, dtype=jnp.float32,
                            num_experts=4, moe_top_k=2,
                            expert_capacity_factor=8.0)
    layer = MoEMLP(cfg)
    x = jax.random.normal(jax.random.key(2), (2, 8, cfg.embed_dim),
                          jnp.float32)
    variables = layer.init(jax.random.key(3), x)
    y = layer.apply(variables, x)

    p = variables["params"]
    wr = np.asarray(p["router"]["kernel"], np.float64)
    w1 = np.asarray(p["w1"], np.float64)
    w2 = np.asarray(p["w2"], np.float64)
    xt = np.asarray(x, np.float64).reshape(-1, cfg.embed_dim)
    logits = xt @ wr
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    gelu = lambda v: 0.5 * v * (1 + np.tanh(
        np.sqrt(2 / np.pi) * (v + 0.044715 * v ** 3)))
    ref = np.zeros_like(xt)
    for n in range(xt.shape[0]):
        order = np.argsort(-probs[n])
        e1, e2 = order[0], order[1]
        g1, g2 = probs[n, e1], probs[n, e2]
        z = g1 + g2
        ref[n] = (g1 / z) * (gelu(xt[n] @ w1[e1]) @ w2[e1]) \
            + (g2 / z) * (gelu(xt[n] @ w1[e2]) @ w2[e2])
    np.testing.assert_allclose(np.asarray(y).reshape(-1, cfg.embed_dim),
                               ref, rtol=2e-4, atol=2e-5)


def test_pipelined_lm_matches_sequential_logits():
    """PipelinedLM with re-stacked identical parameters produces the
    SAME logits as the stock TransformerLM (4 stages x 1 layer)."""
    from horovod_tpu.parallel import PipelinedLM

    cfg = TransformerConfig(vocab_size=64, num_layers=4, num_heads=4,
                            head_dim=8, max_seq_len=16,
                            dtype=jnp.float32)
    mesh = spmd.create_mesh({"stage": 4}, devices=jax.devices()[:4])
    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, 64, (4, 16)), jnp.int32)

    lm = TransformerLM(cfg)
    variables = jax.jit(lm.init)(jax.random.key(0), tokens)
    ref_logits = jax.jit(lm.apply)(variables, tokens)

    plm = PipelinedLM(cfg, mesh, num_microbatches=2)
    params = plm.from_transformer_params(variables)
    logits = plm.apply(params, tokens)
    np.testing.assert_allclose(np.asarray(logits),
                               np.asarray(ref_logits), atol=2e-4)


def test_pipelined_lm_trains_with_dp():
    """dp x pp on the full flagship model: loss decreases under SGD
    through the pipelined tower."""
    from horovod_tpu.parallel import PipelinedLM
    from horovod_tpu.models.transformer import lm_loss

    cfg = TransformerConfig(vocab_size=64, num_layers=2, num_heads=4,
                            head_dim=8, max_seq_len=16,
                            dtype=jnp.float32)
    mesh = spmd.create_mesh({"data": 2, "stage": 2},
                            devices=jax.devices()[:4])
    tokens = jnp.asarray(
        np.tile(np.arange(16, dtype=np.int32)[None], (8, 1)))

    plm = PipelinedLM(cfg, mesh, num_microbatches=2, data_axis="data")
    params = plm.init(jax.random.key(0), tokens)

    @jax.jit
    def loss_fn(p):
        return lm_loss(plm.apply(p, tokens), tokens)

    grad = jax.grad(loss_fn)
    losses = [float(loss_fn(params))]
    for _ in range(6):
        params = jax.tree_util.tree_map(
            lambda a, g: a - 0.5 * g, params, grad(params))
        losses.append(float(loss_fn(params)))
    assert losses[-1] < losses[0], losses


def test_pipelined_lm_rejects_bad_configs():
    from horovod_tpu.parallel import PipelinedLM
    mesh = spmd.create_mesh({"stage": 4}, devices=jax.devices()[:4])
    with pytest.raises(ValueError, match="divide evenly"):
        PipelinedLM(TransformerConfig(vocab_size=64, num_layers=3,
                                      num_heads=2, head_dim=4,
                                      dtype=jnp.float32),
                    mesh, num_microbatches=2)
    with pytest.raises(ValueError, match="homogeneous"):
        PipelinedLM(TransformerConfig(vocab_size=64, num_layers=4,
                                      num_heads=2, head_dim=4,
                                      dtype=jnp.float32, num_experts=2),
                    mesh, num_microbatches=2)


# ---------------------------------------------------------------------------
# Ulysses (all-to-all) sequence parallelism
# ---------------------------------------------------------------------------

def test_ulysses_matches_reference():
    """Sequence sharded over 4 devices via all-to-all must reproduce
    single-device causal attention exactly (each device attends over
    the full sequence — no approximation anywhere)."""
    from horovod_tpu.parallel import make_ulysses_attention
    mesh = spmd.create_mesh({"data": 1, "seq": 4},
                            devices=jax.devices()[:4])
    b, s, h, d = 2, 16, 4, 8
    rng = np.random.RandomState(2)
    q = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    attn = make_ulysses_attention(mesh, data_axis="data",
                                  seq_axis="seq")
    out = attn(q, k, v, True)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(causal_attention(q, k, v)),
                               atol=2e-5)


def test_ulysses_trainer_matches_dense_loss():
    """Training loss with Ulysses attention == dense attention loss
    (mirror of the ring-attention equivalence test)."""
    import optax
    from horovod_tpu.parallel import make_ulysses_attention
    mesh = spmd.create_mesh({"data": 2, "seq": 4})
    attn = make_ulysses_attention(mesh, data_axis="data",
                                  seq_axis="seq")
    tokens = np.tile(np.arange(16, dtype=np.int32)[None], (4, 1))
    batch = {"tokens": tokens}

    dense = Trainer(TransformerLM(_tiny_cfg()), mesh, optax.sgd(1e-2),
                    TrainerConfig(model_axis=None, seq_axis="seq"))
    ulys = Trainer(TransformerLM(_tiny_cfg(attention_fn=attn)), mesh,
                   optax.sgd(1e-2),
                   TrainerConfig(model_axis=None, seq_axis="seq"))
    s0 = dense.init(jax.random.key(7), batch)
    s1 = ulys.init(jax.random.key(7), batch)
    _, l0 = dense.train_step(s0, batch)
    _, l1 = ulys.train_step(s1, batch)
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-4)


def test_ulysses_rejects_indivisible_heads():
    from horovod_tpu.parallel import make_ulysses_attention
    mesh = spmd.create_mesh({"data": 1, "seq": 4},
                            devices=jax.devices()[:4])
    attn = make_ulysses_attention(mesh, data_axis="data",
                                  seq_axis="seq")
    q = jnp.zeros((1, 16, 3, 8), jnp.float32)  # 3 heads over 4 devices
    with pytest.raises(ValueError, match="divisible"):
        attn(q, q, q, True)


def test_seq_parallel_attention_respects_causal_flag():
    """attention_fn(q, k, v, causal=False) must run UNmasked attention
    (regression: the flag used to be silently dropped)."""
    from horovod_tpu.parallel import (
        make_ring_attention, make_ulysses_attention,
    )
    mesh = spmd.create_mesh({"data": 1, "seq": 4},
                            devices=jax.devices()[:4])
    b, s, h, d = 1, 16, 4, 8
    rng = np.random.RandomState(9)
    q = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    ref = causal_attention(q, k, v, causal=False)
    uly = make_ulysses_attention(mesh, data_axis="data", seq_axis="seq")
    np.testing.assert_allclose(np.asarray(uly(q, k, v, False)),
                               np.asarray(ref), atol=2e-5)
    ring = make_ring_attention(mesh, data_axis="data", seq_axis="seq",
                               model_axis=None)
    np.testing.assert_allclose(np.asarray(ring(q, k, v, False)),
                               np.asarray(ref), atol=2e-5)


# ---------------------------------------------------------------------------
# FSDP (ZeRO-3-style) parameter sharding
# ---------------------------------------------------------------------------

def test_fsdp_sharding_picks_largest_free_divisible_dim():
    from horovod_tpu.parallel import fsdp_sharding
    mesh = spmd.create_mesh({"data": 4, "model": 2})
    params = {
        "big": np.zeros((12, 64), np.float32),      # dim1 largest, both div by 4
        "tall": np.zeros((64, 6), np.float32),      # only dim0 divisible
        "bias": np.zeros((64,), np.float32),        # < min_size: untouched
        "odd": np.zeros((33, 35), np.float32),      # nothing divisible by 4
    }
    sh = fsdp_sharding(params, mesh, axis="data", min_size=128)
    assert sh["big"].spec == P(None, "data")
    assert sh["tall"].spec == P("data", None)
    assert sh["bias"].spec == P()
    assert sh["odd"].spec == P()


def test_fsdp_sharding_composes_with_tp_base():
    from jax.sharding import NamedSharding
    from horovod_tpu.parallel import fsdp_sharding
    mesh = spmd.create_mesh({"data": 4, "model": 2})
    params = {"k": np.zeros((16, 64), np.float32)}
    base = {"k": NamedSharding(mesh, P(None, "model"))}
    sh = fsdp_sharding(params, mesh, axis="data", base=base,
                       min_size=128)
    # dim1 is claimed by tp; fsdp must take the remaining dim0
    assert sh["k"].spec == P("data", "model")


def test_trainer_fsdp_shards_params_and_opt_state():
    import optax
    mesh = spmd.create_mesh({"data": 8})
    model = TransformerLM(_tiny_cfg())
    trainer = Trainer(model, mesh, optax.adam(1e-2),
                      TrainerConfig(model_axis=None, fsdp_axis="data"))
    tokens = np.tile(np.arange(16, dtype=np.int32)[None], (8, 1))
    state = trainer.init(jax.random.key(0), {"tokens": tokens})

    def specs(tree):
        return {jax.tree_util.keystr(k): getattr(v.sharding, "spec", P())
                for k, v in jax.tree_util.tree_flatten_with_path(tree)[0]}

    psp = specs(state["params"])
    sharded = [k for k, s in psp.items() if "data" in str(s)]
    assert sharded, psp  # the big matrices picked up the fsdp axis
    assert any("embedding" in k for k in sharded), sharded
    # optimizer moments inherit the parameter shardings via jit
    osp = specs(state["opt_state"])
    assert any("data" in str(s) for s in osp.values()), osp

    state, l0 = trainer.train_step(state, {"tokens": tokens})
    state, l1 = trainer.train_step(state, {"tokens": tokens})
    assert np.isfinite(l0) and float(l1) < float(l0)


def test_trainer_fsdp_matches_plain_dp():
    """FSDP is a memory layout, not a math change: training under
    fsdp_axis must track the plain data-parallel run."""
    import optax
    tokens = np.tile(np.arange(16, dtype=np.int32)[None], (8, 1))
    batch = {"tokens": tokens}

    def run(fsdp):
        mesh = spmd.create_mesh({"data": 8})
        trainer = Trainer(
            TransformerLM(_tiny_cfg()), mesh, optax.sgd(1e-2),
            TrainerConfig(model_axis=None,
                          fsdp_axis="data" if fsdp else None))
        state = trainer.init(jax.random.key(0), batch)
        losses = []
        for _ in range(3):
            state, loss = trainer.train_step(state, batch)
            losses.append(float(loss))
        return losses

    np.testing.assert_allclose(run(False), run(True), rtol=2e-4)


# ---------------------------------------------------------------------------
# Chunked vocab loss
# ---------------------------------------------------------------------------

def test_chunked_lm_loss_matches_default():
    """make_chunked_lm_loss must equal the default full-logits loss in
    value AND gradient (fp32 tolerance), including a chunk size that
    does not divide seq-1 (padding path) and MoE aux handling."""
    import optax
    from horovod_tpu.parallel import make_chunked_lm_loss
    from horovod_tpu.parallel.trainer import _default_lm_loss

    cfg = TransformerConfig(vocab_size=97, num_layers=2, num_heads=2,
                            head_dim=8, max_seq_len=24,
                            dtype=jnp.float32, num_experts=2,
                            moe_every=2)
    model = TransformerLM(cfg)
    tokens = np.random.RandomState(0).randint(
        0, 97, (3, 24)).astype(np.int32)
    params = jax.jit(model.init)(jax.random.key(0), tokens)

    # seq-1 = 23, chunk 8 -> pad 1
    chunked = make_chunked_lm_loss(chunk=8)

    def l_default(p):
        return _default_lm_loss(model.apply, p, {"tokens": tokens})

    def l_chunked(p):
        return chunked(model.apply, p, {"tokens": tokens})

    v0, g0 = jax.value_and_grad(l_default)(params)
    v1, g1 = jax.value_and_grad(l_chunked)(params)
    np.testing.assert_allclose(float(v0), float(v1), rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5), g0, g1)


def test_chunked_lm_loss_trains_in_trainer():
    import optax
    from horovod_tpu.parallel import make_chunked_lm_loss
    mesh = spmd.create_mesh({"data": 8})
    trainer = Trainer(TransformerLM(_tiny_cfg()), mesh, optax.adam(1e-2),
                      TrainerConfig(model_axis=None),
                      loss_fn=make_chunked_lm_loss(chunk=8))
    tokens = np.tile(np.arange(16, dtype=np.int32)[None], (8, 1))
    batch = {"tokens": tokens}
    state = trainer.init(jax.random.key(0), batch)
    losses = []
    for _ in range(5):
        state, loss = trainer.train_step(state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0]
