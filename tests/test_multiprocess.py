"""Multi-process integration tests: spawn N real processes that
negotiate through the TCP controller and move data through the socket
backend — the TPU build's version of the reference's ``mpirun -np 2
pytest`` legs (reference: .travis.yml:109-122, test/common.py:25-57)."""

import os
import signal
import socket
import subprocess
import sys
import tempfile

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _base_env(extra_env=None):
    """Worker-process env hygiene shared by every spawning test."""
    base = dict(os.environ)
    base["PYTHONPATH"] = REPO + os.pathsep + base.get("PYTHONPATH", "")
    base.setdefault("JAX_PLATFORMS", "cpu")
    # Keep the TPU plugin's sitecustomize from overriding jax_platforms
    # back to the tunneled TPU inside worker processes.
    base.pop("PALLAS_AXON_POOL_IPS", None)
    # Arm the runtime lockdep (common/lockdep.py) in every spawned
    # world: all mp scenarios double as lock-inversion regression tests
    # — an acquisition-order inversion anywhere in the runtime raises
    # LockInversionError instead of someday deadlocking a real job.
    base.setdefault("HOROVOD_TPU_LOCKCHECK", "1")
    # Same deal for the thread-affinity sanitizer (common/threadcheck
    # .py): every checked field's cross-role write discipline is
    # re-proven by every spawned world, raising ThreadAffinityError
    # at the violating write instead of losing an update in prod.
    base.setdefault("HOROVOD_TPU_THREADCHECK", "1")
    # The default-on flight recorder dumps into CWD on every abort;
    # point every spawned world at a throwaway dir so abort-path tests
    # don't litter the checkout with pid-unique postmortems (tests
    # that assert on dumps override this with their own tmp_path).
    base.setdefault("HOROVOD_TPU_FLIGHT_DIR",
                    tempfile.mkdtemp(prefix="hvd-flight-test."))
    if extra_env:
        base.update(extra_env)
    return base


def run_scenario(scenario: str, size: int, timeout: float = 90.0,
                 extra_env=None, per_rank_env=None, expect_rc=None):
    """``expect_rc`` maps rank -> expected returncode for ranks that
    are SUPPOSED to die (fault-injection victims: a SIGKILL'd rank
    exits -9, not 0). Every other rank must exit 0.

    Each rank also gets a hard in-process deadline a bit under
    ``timeout`` (HOROVOD_TEST_DEADLINE -> faulthandler alarm in
    mp_scenarios.main): a deadlocked rank self-reports with thread
    stacks instead of relying on this parent's kill."""
    port = _free_port()
    procs = []
    base = _base_env(extra_env)
    base.setdefault("HOROVOD_TEST_DEADLINE",
                    str(max(5.0, timeout - 5.0)))
    for rank in range(size):
        env = dict(base)
        if per_rank_env:
            env.update(per_rank_env(rank))
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "tests.mp_scenarios", scenario,
             str(rank), str(size), str(port)],
            cwd=REPO, env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
    failures = []
    for rank, p in enumerate(procs):
        try:
            out, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise AssertionError(
                f"scenario {scenario} rank {rank} timed out")
        want = 0 if expect_rc is None else expect_rc.get(rank, 0)
        if p.returncode != want:
            failures.append((rank, p.returncode, out.decode()))
    assert not failures, "\n".join(
        f"--- rank {r} exited {rc} ---\n{o}" for r, rc, o in failures)


@pytest.mark.parametrize("size", [2, 4])
def test_allreduce(size):
    run_scenario("allreduce", size)


def test_allreduce_fused():
    run_scenario("allreduce_fused", 2)


def test_allreduce_multi_dtype():
    run_scenario("allreduce_multi_dtype", 2)


@pytest.mark.parametrize("size", [2, 3])
def test_allgather(size):
    run_scenario("allgather", size)


def test_broadcast():
    run_scenario("broadcast", 2)


def test_broadcast_nonzero_root_three_ranks():
    """size > 2 with every root: the root's payload must not be echoed
    back to it by the coordinator fan-out."""
    run_scenario("broadcast", 3)


def test_alltoall():
    run_scenario("alltoall", 2)


def test_reducescatter():
    run_scenario("reducescatter", 2)


def test_barrier():
    run_scenario("barrier", 2)


def test_wide_world_smoke():
    """12 ranks on one host: the coordinator's fan-in (native poll
    gather), the shm plane, and FUSED batches all hold up beyond the
    2-4 rank worlds the rest of the suite uses."""
    run_scenario("allreduce", 12, timeout=180.0)
    run_scenario("allreduce_fused", 12, timeout=180.0)


def test_wide_world_hier_smoke():
    """16 ranks as 4 fake hosts x 4: the deepest hierarchy the suite
    runs — 3 local leaves + 3 aggregate root channels at the
    coordinator, 3-leaf relays at every remote root — with exact
    results on plain and FUSED batches."""
    run_scenario(
        "allreduce", 16, timeout=300.0,
        per_rank_env=lambda rank: {
            "HOROVOD_HOSTNAME": f"fakehost{rank // 4}"})
    run_scenario(
        "allreduce_fused", 16, timeout=300.0,
        per_rank_env=lambda rank: {
            "HOROVOD_HOSTNAME": f"fakehost{rank // 4}"})


@pytest.mark.parametrize("size", [3, 4])
def test_ring_allreduce(size):
    """Large payloads take the 2-phase ring data plane (threshold
    lowered so modest tensors cross it); mixed sizes exercise both
    paths against one established ring. Shm is disabled so the socket
    backend — the ring's host — is actually selected."""
    run_scenario("ring_allreduce", size, timeout=120.0,
                 extra_env={"HOROVOD_TPU_RING_THRESHOLD": "1024",
                            "HOROVOD_TPU_SHM": "0"})


def test_ring_establishment_failure_falls_back_to_star():
    run_scenario("ring_fallback", 3, timeout=120.0,
                 extra_env={"HOROVOD_TPU_RING_THRESHOLD": "1024",
                            "HOROVOD_TPU_SHM": "0"})


def test_shm_collectives():
    """Same-host world -> the shared-memory data plane carries every
    collective (reference analog: MPI_Win_allocate_shared staging,
    mpi_operations.cc:179-329)."""
    run_scenario("shm_collectives", 3, timeout=120.0)


def test_shm_establishment_failure_falls_back_to_socket():
    run_scenario("shm_fallback", 2, timeout=120.0)


def test_shm_disabled_for_multihost_topology():
    """Forced 2-host topology with ONE rank per host: nothing to gain
    from shared memory, the shm backend must stand down."""
    run_scenario(
        "shm_multihost_disabled", 2, timeout=120.0,
        per_rank_env=lambda rank: {
            "HOROVOD_HOSTNAME": f"fakehost{rank}"})


def test_shm_hierarchical_allreduce_two_hosts():
    """4 ranks on 2 fake hosts: allreduce takes the hierarchical
    local-reduce -> cross-roots -> local-broadcast shm path."""
    run_scenario(
        "shm_hier_allreduce", 4, timeout=180.0,
        per_rank_env=lambda rank: {
            "HOROVOD_HOSTNAME": f"fakehost{rank // 2}"})


@pytest.mark.parametrize("scenario", [
    "allreduce", "allreduce_fused", "allgather", "broadcast",
    "alltoall", "reducescatter"])
def test_socket_backend_forced(scenario):
    """With shm disabled, every collective still runs correctly on the
    raw TCP socket backend (its default-world coverage moved to shm
    when that plane became the same-host default)."""
    run_scenario(scenario, 2, extra_env={"HOROVOD_TPU_SHM": "0"})


def test_shm_hierarchical_allreduce_uneven_hosts():
    """3 ranks split 2+1: the solo host's local reduce is the identity
    and its root still joins the cross exchange."""
    run_scenario(
        "shm_hier_allreduce", 3, timeout=180.0,
        per_rank_env=lambda rank: {
            "HOROVOD_HOSTNAME": f"fakehost{min(rank, 1)}"})


def test_hier_controller_two_hosts():
    """4 ranks on 2 fake hosts: remote leaves migrate behind their
    local root, coordinator fan-in drops to 2, and the full collective
    mix stays exact end-to-end through the aggregated control plane."""
    run_scenario(
        "hier_controller", 4, timeout=180.0,
        per_rank_env=lambda rank: {
            "HOROVOD_HOSTNAME": f"fakehost{rank // 2}"})


def test_hier_controller_uneven_hosts():
    """5 ranks split 2+3: the remote host aggregates three ranks; the
    rank-order of frames inside the aggregate must survive."""
    run_scenario(
        "hier_controller", 5, timeout=180.0,
        per_rank_env=lambda rank: {
            "HOROVOD_HOSTNAME": f"fakehost{min(rank // 2, 1)}"})


def test_hier_controller_three_hosts():
    """6 ranks on 3 fake hosts (2 each): multiple aggregate channels
    at the coordinator simultaneously."""
    run_scenario(
        "hier_controller", 6, timeout=240.0,
        per_rank_env=lambda rank: {
            "HOROVOD_HOSTNAME": f"fakehost{rank // 2}"})


def test_hier_controller_disabled_falls_back_flat():
    """HOROVOD_TPU_HIER_CONTROLLER=0 on the same topology keeps the
    flat star: no migration, no aggregate channels."""
    run_scenario(
        "flat_controller_multihost", 4, timeout=180.0,
        extra_env={"HOROVOD_TPU_HIER_CONTROLLER": "0"},
        per_rank_env=lambda rank: {
            "HOROVOD_HOSTNAME": f"fakehost{rank // 2}"})


def test_shape_mismatch_error():
    run_scenario("shape_mismatch_error", 2)


def test_dtype_mismatch_error():
    run_scenario("dtype_mismatch_error", 2)


def test_root_rank_mismatch_error():
    run_scenario("root_rank_mismatch_error", 2)


def test_out_of_order_submission():
    run_scenario("rank_subset_order", 2)


def test_topology():
    run_scenario("topology", 2)


def test_stall_shutdown():
    run_scenario(
        "stall_shutdown", 2, timeout=60.0,
        extra_env={"HOROVOD_STALL_CHECK_TIME_SECONDS": "1",
                   "HOROVOD_STALL_SHUTDOWN_TIME_SECONDS": "2"})


def test_torch_distributed_optimizer():
    run_scenario("torch_optimizer", 2, timeout=120.0)


def test_jax_adapter_host_path():
    run_scenario("jax_adapter", 2)


def test_torch_allreduce_grad():
    """Backward through hvd.allreduce matches the reference's autograd
    semantics."""
    run_scenario("torch_allreduce_grad", 2, timeout=120.0)


def test_torch_adam_state_broadcast():
    run_scenario("torch_adam_state", 2, timeout=120.0)


def test_torch_opt_state_asymmetric_broadcast():
    """Checkpoint-restore shape: only rank 0 has optimizer state; the
    broadcast must materialize worker state instead of hanging."""
    run_scenario("torch_opt_state_asymmetric", 2, timeout=120.0)


def test_keras_distributed_optimizer():
    run_scenario("keras_optimizer", 2, timeout=180.0)


def test_tf_distributed_gradient_tape():
    run_scenario("tf_tape", 2, timeout=180.0)


def test_tf_allreduce_grad():
    run_scenario("tf_allreduce_grad", 2, timeout=180.0)


def test_tf_sparse_as_dense():
    """sparse_as_dense=True matches the IndexedSlices gather path
    bit-for-bit on an embedding gradient."""
    run_scenario("tf_sparse_as_dense", 2, timeout=180.0)


def test_tf_broadcast_hook():
    """BroadcastGlobalVariablesHook drives a real TF1
    MonitoredTrainingSession broadcast."""
    run_scenario("tf_broadcast_hook", 2, timeout=180.0)


@pytest.mark.slow
def test_tf_gather_bcast_grad():
    """Differentiable allgather (variable dim-0) and broadcast
    (root-only gradient), 3 ranks."""
    run_scenario("tf_gather_bcast_grad", 3, timeout=180.0)


def test_torch_gather_bcast_grad():
    """Same contract through the torch autograd Functions, plus the
    non-differentiable in-place broadcast_."""
    run_scenario("torch_gather_bcast_grad", 3, timeout=180.0)


def test_tfkeras_facade():
    run_scenario("tfkeras_facade", 2, timeout=240.0)


def test_scalar_broadcast():
    run_scenario("scalar_broadcast", 2)


@pytest.mark.parametrize("plane", ["shm", "socket"])
def test_mixed_op_storm(plane):
    """Async mixed-type collectives in per-rank-random submission
    order, on both host planes."""
    extra = {} if plane == "shm" else {"HOROVOD_TPU_SHM": "0"}
    run_scenario("mixed_op_storm", 3, timeout=120.0, extra_env=extra)


@pytest.mark.parametrize("plane", ["shm", "socket"])
def test_grouped_allreduce(plane):
    """Grouped submission: exact values per member, per-member average
    semantics, and all-or-nothing error surfacing with a usable world
    afterwards."""
    extra = {} if plane == "shm" else {"HOROVOD_TPU_SHM": "0"}
    run_scenario("grouped_allreduce", 3, timeout=120.0, extra_env=extra)


@pytest.mark.parametrize("plane", ["shm", "socket"])
def test_fused_allgather(plane):
    """ALLGATHER response fusion: multi-entry batches execute with
    entry-major displacement unpack on both host planes; mixed dtypes
    never share a batch."""
    extra = {"HOROVOD_CYCLE_TIME": "25"}
    if plane == "socket":
        extra["HOROVOD_TPU_SHM"] = "0"
    run_scenario("fused_allgather", 3, timeout=120.0, extra_env=extra)


def test_sparse_allgather_fusion():
    """word2vec-shaped sparse traffic (values+indices allgather pairs)
    executes as a few fused batches per step, not per-tensor singles."""
    run_scenario("sparse_allgather_fusion", 3, timeout=120.0,
                 extra_env={"HOROVOD_CYCLE_TIME": "25"})


def test_grouped_allreduce_atomic():
    """All group members land in ONE fused response even with the
    1 ms cycle ticking and a concurrent thread submitting singles."""
    run_scenario("grouped_atomic", 2, timeout=180.0)


@pytest.mark.parametrize("plane,ranks", [
    ("shm", 3), ("socket", 3), ("shm", 6)])
def test_coordinator_fuzz(plane, ranks):
    """240 seeded mixed collectives, per-rank-random submission order,
    overlapping waves, on both host planes (and a wider 6-rank world)
    — every value exact."""
    extra = {} if plane == "shm" else {"HOROVOD_TPU_SHM": "0"}
    run_scenario("coordinator_fuzz", ranks, timeout=300.0,
                 extra_env=extra)


@pytest.mark.parametrize("plane", ["shm", "socket"])
def test_response_cache_steady_state(plane):
    """Steady-state traffic negotiates through the bitmask fast path
    (hit rate ~100%, fully cached cycles observed), stays exact, keeps
    the cache bit-identical across ranks, and invalidates coherently
    on shape/dtype changes and skewed submission."""
    extra = {} if plane == "shm" else {"HOROVOD_TPU_SHM": "0"}
    run_scenario("response_cache_steady", 3, timeout=120.0,
                 extra_env=extra)


def test_response_cache_steady_state_hier_controller():
    """Same steady-state contract with the hit bitmasks AND-reduced at
    each fake host's local root before reaching the coordinator (the
    CACHED_AGG fold in the gather tree)."""
    run_scenario(
        "response_cache_steady", 4, timeout=180.0,
        per_rank_env=lambda rank: {
            "HOROVOD_HOSTNAME": f"fakehost{rank // 2}"})


def test_response_cache_capacity_eviction_coherent():
    """A tiny capacity forces constant LRU eviction; the eviction order
    (and thus slot reuse) must be world-identical and values exact —
    including names that come back after being evicted."""
    run_scenario("response_cache_eviction", 3, timeout=180.0,
                 extra_env={"HOROVOD_CACHE_CAPACITY": "8"})


def test_response_cache_disabled_via_env():
    """HOROVOD_CACHE_ENABLED=0 falls back to full negotiation on every
    rank (homogeneous) and the whole collective mix stays exact."""
    run_scenario("mixed_op_storm", 3, timeout=120.0,
                 extra_env={"HOROVOD_CACHE_ENABLED": "0"})


def test_response_cache_disabled_hier_two_rank_host():
    """Cache off + a 2-rank remote host: the local root relays an
    UNFOLDED per-rank pack on the request tag, whose raw leading
    byte (the u32 frame count, 2) collides with the CACHED_AGG kind —
    the PACKED envelope must disambiguate (regression: the coordinator
    once sniffed the count byte as a folded cache frame and aborted
    with a spurious divergence error)."""
    run_scenario(
        "mixed_op_storm", 4, timeout=180.0,
        extra_env={"HOROVOD_CACHE_ENABLED": "0"},
        per_rank_env=lambda rank: {
            "HOROVOD_HOSTNAME": f"fakehost{rank // 2}"})


def test_response_cache_spec_hier_two_rank_host():
    """Speculative fused frames through a 2-rank remote host: payload
    frames cannot be mask-folded, so the root forwards them under the
    PACKED envelope and the coordinator still reduces the unanimous
    cycle inline — steady state, exact values, coherent caches."""
    run_scenario(
        "response_cache_steady", 4, timeout=180.0,
        extra_env={"HOROVOD_TPU_SHM": "0"},
        per_rank_env=lambda rank: {
            "HOROVOD_HOSTNAME": f"fakehost{rank // 2}"})


def test_cache_control_plane_byte_budget():
    """Steady-state cycles must move O(capacity/8) control bytes per
    rank — a counting wrapper on Channel send/recv asserts the
    per-cycle budget on a worker rank at world_size=4 (speculative
    fused frames carry tensor data on the request tag by design, so
    they are disabled to expose the mask-path budget)."""
    run_scenario("cache_byte_budget", 4, timeout=180.0,
                 extra_env={"HOROVOD_CACHE_CAPACITY": "256",
                            "HOROVOD_CACHE_SPECULATIVE": "0"})


def test_response_cache_heterogeneous_speculation_knob():
    """HOROVOD_CACHE_SPECULATIVE off on ONE rank only: the fused
    single-round path requires unanimity per cycle, so the world
    falls back to the classic two-round cached path everywhere —
    correct results, zero completed speculative cycles."""
    run_scenario(
        "response_cache_hetero_spec", 3, timeout=120.0,
        extra_env={"HOROVOD_TPU_SHM": "0"},
        per_rank_env=lambda rank: (
            {"HOROVOD_CACHE_SPECULATIVE": "0"} if rank == 1 else {}))


def test_kitchen_sink_all_subsystems(tmp_path):
    """Cross-subsystem integration: autotune (+log), timeline (+cycle
    marks), hierarchical shm over a fake 2-host topology, and the stall
    inspector armed — all in one 4-rank world under shuffled mixed
    traffic with a mid-stream coordinator ERROR. Afterwards both
    artifacts must be well-formed: the timeline is valid Chrome-tracing
    JSON with negotiation + execution + cycle vocabulary, and the
    autotune CSV has sample rows."""
    timeline = str(tmp_path / "ks_timeline.json")
    atlog = str(tmp_path / "ks_autotune.csv")
    run_scenario(
        "kitchen_sink", 4, timeout=300.0,
        extra_env={
            "HOROVOD_TIMELINE": timeline,
            "HOROVOD_TIMELINE_MARK_CYCLES": "1",
            "HOROVOD_AUTOTUNE": "1",
            "HOROVOD_AUTOTUNE_LOG": atlog,
            # first CSV row needs (warmup + 3 median scores) busy
            # cycles per sampled step; with the defaults that is 40
            # cycles, which the storm's fused/cached traffic does not
            # deterministically produce (the pre-PR-20 flake). One
            # step per sample + one warmup sample = 4 busy cycles,
            # well under the 20 rounds the scenario always drives.
            "HOROVOD_AUTOTUNE_WARMUP_SAMPLES": "1",
            "HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE": "1",
            "HOROVOD_HIERARCHICAL_ALLREDUCE": "1",
            "HOROVOD_HIERARCHICAL_ALLGATHER": "1",
            "HOROVOD_STALL_CHECK_TIME_SECONDS": "60",
        },
        per_rank_env=lambda rank: {
            "HOROVOD_HOSTNAME": f"fakehost{rank // 2}"})

    import json
    with open(timeline) as f:
        events = json.load(f)
    names = {e.get("name") for e in events}
    for required in ("NEGOTIATE_ALLREDUCE", "NEGOTIATE_BROADCAST",
                     "NEGOTIATE_ALLGATHER", "ALLREDUCE", "BROADCAST",
                     "CYCLE_START"):
        assert required in names, (required, sorted(names)[:40])

    with open(atlog) as f:
        rows = [ln for ln in f.read().splitlines() if ln.strip()]
    assert len(rows) >= 2, rows  # header + at least one sample


@pytest.mark.parametrize("plane", ["shm", "socket"])
def test_bf16_host_path(plane):
    extra = {} if plane == "shm" else {"HOROVOD_TPU_SHM": "0"}
    run_scenario("bf16_host_path", 2, extra_env=extra)


def test_secret_mismatch_fails_init_loudly():
    """Ranks with different HOROVOD_SECRET_KEY must fail init with
    authentication/timeout errors, never connect or hang (reference
    analog: the launcher's per-run HMAC secret contract)."""
    port = _free_port()
    base = _base_env({"HOROVOD_CONTROLLER_ADDR": "127.0.0.1",
                      "HOROVOD_CONTROLLER_PORT": str(port),
                      "HOROVOD_SIZE": "2",
                      "HOROVOD_START_TIMEOUT": "6"})
    code = "import horovod_tpu as hvd; hvd.init()"
    procs = []
    for rank in range(2):
        env = dict(base, HOROVOD_RANK=str(rank),
                   HOROVOD_SECRET_KEY="alpha" if rank == 0 else "beta")
        procs.append(subprocess.Popen(
            [sys.executable, "-c", code], env=env, cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=60)
            outs.append(out.decode())
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    assert all(p.returncode != 0 for p in procs), outs
    assert "ranks connected" in outs[0] or "Timeout" in outs[0], outs[0]
    assert ("ConnectionError" in outs[1] or "HMAC" in outs[1]
            or "closed" in outs[1]), outs[1]


@pytest.mark.parametrize("plane", ["shm", "socket"])
def test_edge_shapes(plane):
    """Zero-size and 0-d tensors through every collective, on both
    host data planes."""
    extra = {} if plane == "shm" else {"HOROVOD_TPU_SHM": "0"}
    run_scenario("edge_shapes", 3, extra_env=extra)


def test_lockcheck_catches_synthetic_inversion():
    """Every mp world runs with HOROVOD_TPU_LOCKCHECK=1 (see
    _base_env); this scenario additionally PROVOKES an inversion and
    asserts the armed lockdep raises it on every rank while real
    collectives stay inversion-free before and after."""
    run_scenario("lockcheck_inversion", 2)


def test_rank_death_fails_survivors_cleanly():
    """Kill one of three ranks mid-job: the other two must error out
    with HorovodInternalError on their next collective, not hang."""
    run_scenario("rank_death", 3, timeout=60.0)


def test_coordinator_death_fails_workers_cleanly():
    """Kill rank 0 (coordinator + controller host): both workers must
    error out on their next collective and shut down, not hang."""
    run_scenario("coordinator_death", 3, timeout=60.0)


def test_rank_death_hier_leaf_fails_survivors_cleanly():
    """Kill a remote LEAF under the hierarchical control plane (4
    ranks, 2 fake hosts): the death must propagate leaf -> local root
    -> coordinator -> world without hanging any tier."""
    run_scenario(
        "rank_death_hier", 4, timeout=90.0,
        per_rank_env=lambda rank: {
            "HOROVOD_HOSTNAME": f"fakehost{rank // 2}"})


# -- fail-fast world abort (heartbeats + ABORT fan-out; see -----------
# docs/fault_tolerance.md). Victims die by fault injection armed via
# HOROVOD_FAULT_SPEC (horovod_tpu/common/faults.py); survivors must
# raise WorldAbortedError NAMING the dead rank, purely in-band — the
# harness timeout/alarm exists only to report a regression, never to
# unblock a passing run.

_HB_ENV = {
    "HOROVOD_HEARTBEAT_INTERVAL": "0.3",
    "HOROVOD_HEARTBEAT_TIMEOUT": "3",
}
_SIGKILL_RC = -signal.SIGKILL


def test_abort_sigkill_leaf_mid_allreduce():
    """SIGKILL rank 1 of 3 just before it executes its 3rd collective:
    both survivors (coordinator included) raise WorldAbortedError
    naming rank 1 within the detection deadline."""
    run_scenario(
        "abort_sigkill_leaf", 3, timeout=60.0,
        extra_env={**_HB_ENV,
                   "HOROVOD_FAULT_SPEC": "rank=1:kill:op=3"},
        expect_rc={1: _SIGKILL_RC})


def test_abort_sigkill_local_root_hier():
    """SIGKILL the second fake host's local root (rank 2 of 4)
    mid-collective: leaves below it, the coordinator above it, and
    the unrelated host's ranks all abort with rank 2 named."""
    run_scenario(
        "abort_sigkill_local_root", 4, timeout=60.0,
        extra_env={**_HB_ENV,
                   "HOROVOD_FAULT_SPEC": "rank=2:kill:op=3"},
        per_rank_env=lambda rank: {
            "HOROVOD_HOSTNAME": f"fakehost{rank // 2}"},
        expect_rc={2: _SIGKILL_RC})


def test_abort_sigkill_coordinator():
    """SIGKILL rank 0 (coordinator + controller socket) mid-
    collective: with no coordinator left to fan the ABORT, each worker
    must detect its dead upward channel itself and name rank 0."""
    run_scenario(
        "abort_sigkill_coordinator", 3, timeout=60.0,
        extra_env={**_HB_ENV,
                   "HOROVOD_FAULT_SPEC": "rank=0:kill:op=3"},
        expect_rc={0: _SIGKILL_RC})


def test_abort_sigkill_mid_cached_cycle():
    """SIGKILL rank 1 deep in bitmask steady state (op=40 of a
    single-tensor loop is long past warmup): the survivors are blocked
    in a bits-frame gather when the victim dies, and must still raise
    WorldAbortedError naming rank 1 within the heartbeat deadline —
    the PR 2 fail-fast invariant holds on the negotiation fast path."""
    run_scenario(
        "abort_sigkill_cached", 3, timeout=60.0,
        extra_env={**_HB_ENV,
                   "HOROVOD_FAULT_SPEC": "rank=1:kill:op=40"},
        expect_rc={1: _SIGKILL_RC})


def test_native_steady_zero_copy_socket():
    """Zero-copy native steady cycle on the socket star at ws=4:
    exact values, native_steady_cycles advancing everywhere, zero
    fallback byte-copies after warmup, and the aliasing contract
    (outputs from step k survive 19 later steps untouched)."""
    run_scenario(
        "native_steady", 4, timeout=120.0,
        extra_env={"HOROVOD_TPU_SHM": "0",
                   "HOROVOD_TPU_METRICS": "1"})


def test_native_steady_alloc_property_shm():
    """The O(1)-allocations steady-step property on the shm data
    plane: hvd_data_copies_total must not move across 25 steady
    steps (the shm plane never defensively copies payload bytes)."""
    run_scenario(
        "native_steady", 4, timeout=120.0,
        extra_env={"HOROVOD_TPU_METRICS": "1"})


def test_native_steady_pure_python_fallback():
    """HOROVOD_NATIVE=0: the whole steady machinery must stay green
    on the pure-Python paths (classic PR 3 fused cycle)."""
    run_scenario(
        "native_steady", 3, timeout=120.0,
        extra_env={"HOROVOD_TPU_SHM": "0",
                   "HOROVOD_TPU_METRICS": "1",
                   "HOROVOD_NATIVE": "0"})


def test_native_hetero_world():
    """Mixed world: rank 1 runs with the native core off, rank 2 with
    HOROVOD_TPU_ZERO_COPY=0 — the CACHED_SPEC wire format is
    byte-identical either way, so values stay exact, fused cycles
    still complete, and the native coordinator keeps its one-call
    steady loop over pure-Python peers."""
    run_scenario(
        "native_hetero", 4, timeout=120.0,
        extra_env={"HOROVOD_TPU_SHM": "0"},
        per_rank_env=lambda rank: (
            {"HOROVOD_NATIVE": "0"} if rank == 1 else
            {"HOROVOD_TPU_ZERO_COPY": "0"} if rank == 2 else {}))


def test_abort_sigkill_mid_native_steady():
    """SIGKILL rank 1 deep in zero-copy steady state (op=40): the
    survivors are blocked inside hvd_steady_worker/coord when the
    victim dies, and must still raise WorldAbortedError naming rank 1
    within the heartbeat deadline — the C loop honors the armed recv
    deadlines."""
    run_scenario(
        "abort_sigkill_native_steady", 3, timeout=60.0,
        extra_env={**_HB_ENV,
                   "HOROVOD_TPU_SHM": "0",
                   "HOROVOD_FAULT_SPEC": "rank=1:kill:op=40"},
        expect_rc={1: _SIGKILL_RC})


def test_abort_sever_mid_native_steady():
    """Abruptly close rank 1's upward control channel deep in
    zero-copy steady state: both sides of the cut converge on a
    structured world abort instead of blocking in the native loop."""
    run_scenario(
        "abort_sever_native_steady", 3, timeout=60.0,
        extra_env={**_HB_ENV,
                   "HOROVOD_TPU_SHM": "0",
                   "HOROVOD_FAULT_SPEC": "rank=1:sever:cycle=30"})


def test_abort_heartbeat_detects_silent_hang():
    """Wedge rank 1's background loop for 10 s WITHOUT killing it (no
    FIN/RST ever reaches the peers — the case TCP error detection
    cannot see): survivors must abort within the 3 s heartbeat
    deadline plus slack, naming rank 1, proving detection is bounded
    by HOROVOD_HEARTBEAT_TIMEOUT rather than by the wedge ending."""
    run_scenario(
        "abort_heartbeat_hang", 3, timeout=60.0,
        extra_env={**_HB_ENV,
                   "HOROVOD_FAULT_SPEC":
                       "rank=1:hang:cycle=20:seconds=10"})


def test_abort_severed_control_link():
    """Fault-inject an abrupt close of rank 1's upward control channel
    (process stays alive): both sides of the cut converge on a world
    abort instead of one side blocking forever."""
    run_scenario(
        "abort_severed_link", 3, timeout=60.0,
        extra_env={**_HB_ENV,
                   "HOROVOD_FAULT_SPEC": "rank=1:sever:cycle=20"})


def test_abort_sigkill_ring_data_plane():
    """SIGKILL rank 1 while payloads ride the 2-phase RING data plane
    (threshold lowered so they do): the survivor whose ring link dies
    must blame the dead NEIGHBOR — not itself, the healthy detecting
    rank — and the abort must still fan to every survivor."""
    run_scenario(
        "abort_sigkill_ring", 3, timeout=60.0,
        extra_env={**_HB_ENV,
                   "HOROVOD_TPU_RING_THRESHOLD": "1024",
                   "HOROVOD_TPU_SHM": "0",
                   "HOROVOD_FAULT_SPEC": "rank=1:kill:op=3"},
        expect_rc={1: _SIGKILL_RC})


def test_ring_data_plane_with_hier_controller():
    """Large payloads on the TCP ring while the CONTROL plane is
    hierarchical: ring rendezvous (listener ports via relayed
    gather/broadcast, peer IPs via the owner-channel map) must still
    connect every rank."""
    run_scenario(
        "ring_allreduce", 4, timeout=240.0,
        extra_env={"HOROVOD_TPU_RING_THRESHOLD": "1024",
                   "HOROVOD_TPU_SHM": "0"},
        per_rank_env=lambda rank: {
            "HOROVOD_HOSTNAME": f"fakehost{rank // 2}"})


# -- overlap tier (HOROVOD_OVERLAP_*: bucketed ready-order dispatch +
# in-flight steady cycles + chunked pipelined transfer;
# docs/performance.md Layer 5). Rank-local scheduling only — the wire
# protocol is unchanged, so heterogeneous knobs must degrade to the
# synchronous path instead of diverging.

_OVERLAP_ENV = {
    "HOROVOD_TPU_SHM": "0",
    "HOROVOD_TPU_METRICS": "1",
    "HOROVOD_OVERLAP_BUCKETS": "4",
    "HOROVOD_OVERLAP_INFLIGHT": "2",
}


def test_overlap_steady_socket():
    """Bucketed grouped allreduce at ws=4: exact sums, multiple
    steady masks, overlap cycles advancing through the in-flight
    runner, hvd_data_copies_total still zero once steady."""
    run_scenario("overlap_steady", 4, timeout=120.0,
                 extra_env=dict(_OVERLAP_ENV))


def test_overlap_steady_compressed_chunked():
    """Same loop under bf16 wire compression with a tiny chunk size:
    every steady cycle rides hvd_steady_worker_chunked (cast
    interleaved with the send) and the values — small integers,
    exactly representable in bf16 — stay exact."""
    run_scenario("overlap_steady", 4, timeout=120.0,
                 extra_env=dict(_OVERLAP_ENV,
                                HOROVOD_COMPRESSION="bf16",
                                HOROVOD_OVERLAP_CHUNK_BYTES="512"))


def test_overlap_bitexact_vs_flat():
    """Bucketed ws=4 training is bit-exact vs an unbucketed replay of
    the same step stream (rounding-sensitive f32 values)."""
    run_scenario("overlap_bitexact", 4, timeout=120.0,
                 extra_env=dict(_OVERLAP_ENV))


def test_overlap_hetero_knobs_degrade():
    """Ranks disagree on every overlap knob: bucket counts differ,
    one rank runs fully synchronous — grants degrade to mask
    intersections and results stay exact and cache-coherent."""
    run_scenario(
        "overlap_hetero", 4, timeout=120.0,
        extra_env=dict(_OVERLAP_ENV),
        per_rank_env=lambda rank: {
            1: {"HOROVOD_OVERLAP_INFLIGHT": "0",
                "HOROVOD_OVERLAP_BUCKETS": "0"},
            2: {"HOROVOD_OVERLAP_BUCKETS": "2"},
        }.get(rank, {}))


def test_overlap_sigkill_mid_inflight():
    """SIGKILL rank 1 deep in bucketed steady state — buckets are in
    flight on the overlap runner when the victim dies. Survivors must
    raise WorldAbortedError naming rank 1 within the deadline."""
    run_scenario(
        "overlap_sigkill", 3, timeout=60.0,
        extra_env=dict(_OVERLAP_ENV, **_HB_ENV,
                       HOROVOD_FAULT_SPEC="rank=1:kill:op=60"),
        expect_rc={1: _SIGKILL_RC})


def test_overlap_sever_mid_inflight():
    """Severed control link while the overlap runner drives native
    cycles: survivors converge on a structured world abort."""
    run_scenario(
        "overlap_sever", 3, timeout=60.0,
        extra_env=dict(_OVERLAP_ENV, **_HB_ENV,
                       HOROVOD_FAULT_SPEC="rank=1:sever:cycle=40"))


# -- elastic worlds (HOROVOD_ELASTIC=1; survive preemption and -------
# re-rendezvous instead of aborting — docs/fault_tolerance.md). The
# victims die by fault injection; the SURVIVORS must re-form a smaller
# world and keep computing EXACT collectives, all under the
# HOROVOD_TEST_DEADLINE alarm guard like every other mp scenario.

_ELASTIC_ENV = {
    **_HB_ENV,
    "HOROVOD_ELASTIC": "1",
    "HOROVOD_ELASTIC_WINDOW": "10",
}


@pytest.mark.parametrize("plane", ["shm", "socket"])
def test_elastic_shrink_survives_sigkill(plane):
    """SIGKILL one of four ranks mid-collective: survivors
    re-rendezvous into ws=3 within 2x the heartbeat timeout and
    complete >= 20 more collectives whose allreduce results match a
    fresh ws=3 world bit-for-bit — on the shm AND socket planes."""
    extra = dict(_ELASTIC_ENV,
                 HOROVOD_FAULT_SPEC="rank=3:kill:op=12",
                 HOROVOD_TPU_METRICS="1")
    if plane == "socket":
        extra["HOROVOD_TPU_SHM"] = "0"
    run_scenario("elastic_shrink", 4, timeout=120.0, extra_env=extra,
                 expect_rc={3: _SIGKILL_RC})


def test_elastic_resize_mid_overlap():
    """Elastic shrink with the overlap tier armed: the kill lands
    while steady cycles run on the in-flight runner; teardown must
    drain the runner cleanly (no wedged completion thread, no stale
    plan replay) and the shrunk world keeps computing exact
    collectives through a fresh runtime."""
    run_scenario(
        "elastic_shrink", 4, timeout=120.0,
        extra_env=dict(_ELASTIC_ENV,
                       HOROVOD_FAULT_SPEC="rank=3:kill:op=12",
                       HOROVOD_TPU_METRICS="1",
                       HOROVOD_TPU_SHM="0",
                       HOROVOD_OVERLAP_INFLIGHT="2",
                       HOROVOD_OVERLAP_BUCKETS="4"),
        expect_rc={3: _SIGKILL_RC})


def test_elastic_coordinator_death_reelects():
    """SIGKILL rank 0 (coordinator + controller socket): old rank 1
    wins the deterministic election, hosts the new controller, and
    the world continues at ws=2."""
    run_scenario(
        "elastic_coordinator_death", 3, timeout=120.0,
        extra_env=dict(_ELASTIC_ENV,
                       HOROVOD_FAULT_SPEC="rank=0:kill:op=8"),
        expect_rc={0: _SIGKILL_RC})


def test_elastic_double_fault_kill_during_rendezvous():
    """A second rank dies ON ENTRY TO the re-rendezvous barrier
    (fault trigger rdzv=1): the barrier waits out its window for the
    silent victim and still closes with the remaining survivors."""
    run_scenario(
        "elastic_double_fault", 4, timeout=120.0,
        extra_env=dict(
            _ELASTIC_ENV,
            HOROVOD_ELASTIC_WINDOW="4",
            HOROVOD_ELASTIC_MIN_WORLD="2",
            HOROVOD_FAULT_SPEC="rank=3:kill:op=8;rank=2:kill:rdzv=1"),
        expect_rc={2: _SIGKILL_RC, 3: _SIGKILL_RC})


def test_elastic_rejoin_after_shrink():
    """Shrink then GROW: after the kill, old rank 0 respawns the lost
    slot as a joiner (the launcher supervision loop's move); it is
    admitted at the next rendezvous barrier, resyncs the State by
    broadcast, and the world trains to completion at full size."""
    run_scenario(
        "elastic_rejoin", 3, timeout=180.0,
        extra_env=dict(_ELASTIC_ENV,
                       HOROVOD_FAULT_SPEC="rank=2:kill:op=8"),
        expect_rc={2: _SIGKILL_RC})


def test_elastic_disabled_keeps_fail_fast():
    """Without HOROVOD_ELASTIC the wrapper is transparent: the PR 2
    WorldAbortedError (naming the dead rank) propagates verbatim."""
    run_scenario(
        "elastic_disabled_fail_fast", 3, timeout=60.0,
        extra_env={**_HB_ENV,
                   "HOROVOD_FAULT_SPEC": "rank=1:kill:op=3"},
        expect_rc={1: _SIGKILL_RC})


# -- self-operation (HOROVOD_SELFOP=1, common/selfop.py): the --------
# supervision policy acting AHEAD of failure — preemption drain,
# telemetry-driven demotion, and the launcher restart from async
# checkpoints — docs/fault_tolerance.md "Self-operation".


def test_selfop_preempt_drains_before_the_kill():
    """A ``preempt`` fault SIGTERMs rank 3 with a 45s grace window:
    the supervision tick drains it out of the world (clean exit 0 —
    no SIGKILL, no blacklist-worthy death) and the survivors resize
    to ws=3 with zero lost steps, every post-resize collective
    bit-exact vs a fresh shrunk world, the resize attributed to
    the policy."""
    run_scenario(
        "selfop_preempt", 4, timeout=120.0,
        extra_env=dict(
            _ELASTIC_ENV,
            HOROVOD_FAULT_SPEC="rank=3:preempt:cycle=40:seconds=45",
            HOROVOD_PREEMPT_GRACE="45",
            HOROVOD_TPU_METRICS="1"))
    # no expect_rc: the preempted rank MUST exit 0 (clean retirement)


def test_selfop_demote_habitual_straggler():
    """A persistent delay fault makes launch rank 1 the last arriver
    in ~every gather; after the churn cooldown the coordinator demotes
    it to the ring tail via a same-size resize. Every member installs
    the identical world-replicated verdict, non-demoted ranks pace
    their cycle top, and the demoted rank's last-arriver share drops
    below the trigger threshold — the skew improves."""
    run_scenario(
        "selfop_demote", 4, timeout=150.0,
        extra_env=dict(
            _ELASTIC_ENV,
            HOROVOD_FAULT_SPEC=(
                "rank=1:delay:cycle=5:ms=20:count=1000000"),
            HOROVOD_SELFOP_DEMOTE_WINDOW="40",
            # the policy consumes the live telemetry plane: the
            # straggler attribution window only arms with it
            HOROVOD_TPU_METRICS="1"))


def test_selfop_below_min_world_restart_from_checkpoints():
    """SIGKILL two of three ranks at the same step — below the min
    world, nothing to shrink to. The launcher's restart budget
    (HOROVOD_TPU_ELASTIC_RESTARTS / --restarts) starts a FRESH world
    which resumes from the async sharded checkpoints at EXACTLY the
    last committed batch (zero staleness here: the kill lands in an
    idle window after the shards were cut), and the final params are
    bit-identical to a never-killed world's."""
    from horovod_tpu.run.launch import HostBlacklist, run_local_elastic

    with tempfile.TemporaryDirectory() as tmp:
        script = os.path.join(tmp, "train.py")
        with open(script, "w") as f:
            f.write(_SELFOP_RESTART_SCRIPT.format(
                repo=REPO, tmp=tmp, total=30, k=12))
        env = {
            "PYTHONPATH": REPO,
            "JAX_PLATFORMS": "cpu",
            "HOROVOD_CYCLE_TIME": "1",
            "HOROVOD_HEARTBEAT_INTERVAL": "0.3",
            "HOROVOD_HEARTBEAT_TIMEOUT": "3",
            "HOROVOD_ELASTIC_WINDOW": "6",
            "HOROVOD_SELFOP_CKPT_DIR": os.path.join(tmp, "ckpt"),
            "HOROVOD_SELFOP_CKPT_INTERVAL": "1",
        }
        rc = run_local_elastic(
            3, [sys.executable, script], env=env, min_np=2,
            blacklist=HostBlacklist(base_s=30.0, retries=0),
            restarts=1)
        assert rc == 0, rc
        for r in (1, 2):
            assert os.path.exists(
                os.path.join(tmp, f"killed.{r}.marker")), \
                "the injected deaths never happened"
        for r in range(3):
            assert os.path.exists(os.path.join(tmp, f"done{r}.ok")), \
                f"rank {r} never finished in the restarted world"


_SELFOP_RESTART_SCRIPT = """\
import faulthandler
import os
import sys
import time

faulthandler.dump_traceback_later(90, exit=True)
sys.path.insert(0, "{repo}")
import numpy as np
import horovod_tpu as hvd
from horovod_tpu.common import elastic

TOTAL = {total}
K = {k}
TMP = "{tmp}"
launch_rank = os.environ.get("HOROVOD_RANK", "")
my_marker = os.path.join(TMP, "killed.%s.marker" % launch_rank)
restarted = os.path.exists(os.path.join(TMP, "killed.1.marker"))

hvd.init()
state = elastic.State(params=np.zeros(16, np.float32), batch=0)


def grad(b, r):
    return np.full(16, float((r + 1) * (b % 7 + 1)), np.float32)


def expected(b, ws):
    return np.full(16, float(sum(range(1, ws + 1)) * (b % 7 + 1)),
                   np.float32)


@elastic.run
def train(state):
    if restarted:
        # the restarted world resumes from the async shards cut in
        # the idle window at batch K — nothing newer was committed
        # before the deaths, so the restore is exact, not just fresh
        assert state.batch == K, state.batch
    while state.batch < TOTAL:
        g = hvd.allreduce(grad(state.batch, hvd.rank()),
                          average=False, name="eg")
        np.testing.assert_array_equal(g, expected(state.batch,
                                                  hvd.size()))
        state.params = state.params + g
        state.batch += 1
        state.commit()
        if state.batch == K:
            # idle across >= 3 checkpoint buckets so every rank
            # persists its shard of the SAME commit seq, then two
            # ranks die at once: ws=1 < min world -> world lost
            time.sleep(3.2)
            if launch_rank in ("1", "2") \\
                    and not os.path.exists(my_marker):
                open(my_marker, "w").close()
                os.kill(os.getpid(), 9)


train(state)
want = np.zeros(16, np.float32)
for b in range(TOTAL):
    want = want + expected(b, hvd.size())
np.testing.assert_array_equal(state.params, want)
open(os.path.join(TMP, "done%s.ok" % hvd.rank()), "w").close()
hvd.shutdown()
"""


def test_rank_subset_init():
    """init(comm=[1, 2]) on 3 processes: the 2-rank subset allreduces
    while the third abstains in a size-1 world."""
    run_scenario("subset_world", 3, timeout=120.0)


def test_subset_world_hierarchical():
    """A rank-subset sub-world spanning two multi-rank fake hosts
    activates the hierarchical control plane inside the subset."""
    run_scenario(
        "subset_world_hier", 6, timeout=240.0,
        per_rank_env=lambda rank: {
            "HOROVOD_HOSTNAME": f"fakehost{rank // 2}"})


# -- multi-tenant collective service (docs/multitenancy.md) -----------------

def test_tenants_two_concurrent_exact():
    """Two tenants spanning one ws=4 fleet train concurrently from
    threads; per-tenant results are exact and tenant A's sequence
    replays bit-identically once B goes idle."""
    run_scenario("tenants_exact", 4, timeout=180.0)


def test_tenants_tensor_parallel_plus_data_parallel():
    """A tensor-parallel tenant (row-parallel partial-sum allreduces +
    column-parallel allgathers) and a data-parallel tenant (averaged
    gradient allreduces) share one ws=4 fleet: exact results on every
    step of both, per-lane QoS accounting, and a bit-identical solo
    replay proving co-scheduling never touched the math."""
    run_scenario("tenants_tp_dp", 4, timeout=180.0)


def test_tenants_priority_weights_skew_cycle_share():
    """A 3:1 weighting measurably shifts the contended cycle share
    toward the heavy tenant (with real deferrals on the light lane)."""
    run_scenario("tenants_priority", 2, timeout=180.0)


def test_tenants_quota_defers_over_quota_tenant():
    """A cycles/sec quota paces the capped tenant (deferred, never
    corrupted) while its unlimited co-tenant runs free."""
    run_scenario("tenants_quota", 2, timeout=180.0)


def test_tenants_sigkill_isolated_to_one_tenant():
    """SIGKILL inside tenant A aborts only A's world; disjoint tenant
    B on the same fleet trains to completion, exact."""
    run_scenario("tenants_fault_isolation", 4, timeout=180.0,
                 expect_rc={1: _SIGKILL_RC})


def test_tenants_service_attach_snapshot_detach():
    """hvdtpurun --service semantics end to end: a 2-rank warm fleet
    serves a 2-replica job that attaches, pulls a parameter snapshot
    via the broadcast fanout, and detaches — no fleet re-rendezvous."""
    gate_port = _free_port()
    run_scenario("tenants_service", 4, timeout=240.0,
                 extra_env={"HOROVOD_TPU_SERVICE": "1",
                            "HOROVOD_TPU_SERVICE_PORT": str(gate_port)})


def test_mxnet_adapter():
    """The MXNet adapter executes end-to-end against the NDArray
    protocol double under a real 2-process world."""
    run_scenario("mxnet", 2, timeout=120.0)


def test_checkpoint_resume(tmp_path_factory):
    import tempfile
    with tempfile.TemporaryDirectory() as tmp:
        run_scenario("checkpoint_resume", 2,
                     extra_env={"HVD_TEST_CKPT_DIR": tmp})


def test_xla_mesh_backend():
    """Real multi-process JAX CPU world -> XlaMeshBackend data plane."""
    run_scenario("xla_backend", 2, timeout=180.0)


def test_xla_mesh_backend_tree_broadcast():
    """HOROVOD_XLA_BCAST=tree: the binary-tree ppermute broadcast
    rendering delivers every root's values (3 ranks exercises the
    non-power-of-two round structure)."""
    run_scenario("xla_backend", 3, timeout=240.0,
                 extra_env={"HOROVOD_XLA_BCAST": "tree"})


def test_xla_async_overlap_end_to_end(tmp_path):
    """Negotiation/execution overlap proven END-TO-END: a deliberately
    slow big XLA collective stays in flight while later cycles
    negotiate and complete small collectives through the real TCP
    gather; rank 0's timeline shows the interleave."""
    run_scenario(
        "xla_async_overlap", 2, timeout=240.0,
        per_rank_env=lambda rank: (
            {"HOROVOD_TIMELINE": str(tmp_path / "overlap.json")}
            if rank == 0 else {}))


def test_xla_ragged_allgather_skew_guard():
    """1 big / 4 tiny ranks: the fused allgather switches to the
    masked-psum (allgatherv-shaped) rendering; uniform shapes keep the
    padded all_gather."""
    run_scenario("xla_ragged_allgather", 5, timeout=300.0)


def test_xla_hierarchical_allreduce():
    run_scenario("xla_hierarchical", 2, timeout=180.0,
                 extra_env={"HOROVOD_HIERARCHICAL_ALLREDUCE": "1"})


def test_xla_hierarchical_allreduce_multihost():
    """Forced 2-host topology (4 ranks): hierarchical allreduce must
    compile and run the factored (cross, local) psum with values
    matching the flat path bit-for-bit."""
    run_scenario(
        "xla_hier_allreduce_multihost", 4, timeout=240.0,
        extra_env={"HOROVOD_HIERARCHICAL_ALLREDUCE": "1"},
        per_rank_env=lambda rank: {
            "HOROVOD_HOSTNAME": f"fakehost{rank // 2}"})


def test_xla_hierarchical_allgather():
    """Forced 2-host topology (2 ranks per fake host): the
    HOROVOD_HIERARCHICAL_ALLGATHER knob must route allgather through
    the two-level (local, cross) path."""
    run_scenario(
        "xla_hierarchical_allgather", 4, timeout=240.0,
        extra_env={"HOROVOD_HIERARCHICAL_ALLGATHER": "1"},
        per_rank_env=lambda rank: {
            "HOROVOD_HOSTNAME": f"fakehost{rank // 2}"})


def test_coordinator_fuzz_through_hier_controller():
    """The 240-job mixed-collective fuzz with every rank's requests
    riding aggregated frames (3 ranks, 2 fake hosts): randomized
    per-rank submission order must still negotiate to one exact total
    order through the relay tier."""
    run_scenario(
        "coordinator_fuzz", 3, timeout=300.0,
        per_rank_env=lambda rank: {
            "HOROVOD_HOSTNAME": f"fakehost{min(rank, 1)}"})


def test_hmac_secret_through_hierarchy():
    """One shared HOROVOD_SECRET_KEY across a fake 2-host topology:
    every tier of the hierarchical control plane (coordinator <-> root
    and root <-> leaf channels, native or Python) authenticates frames
    and collectives stay exact."""
    run_scenario(
        "allreduce", 4,
        extra_env={"HOROVOD_SECRET_KEY": "round5-hier-secret"},
        per_rank_env=lambda rank: {
            "HOROVOD_HOSTNAME": f"fakehost{rank // 2}"})
