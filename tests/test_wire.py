"""Wire protocol round-trip tests (reference analog: the FlatBuffers
encode/decode paths in horovod/common/message.cc:122-215,317-346)."""

import pytest

from horovod_tpu.common.message import (
    DataType, Request, RequestList, RequestType, Response, ResponseList,
    ResponseType,
)
from horovod_tpu.common import wire


def test_request_roundtrip():
    req = Request(request_rank=3, request_type=RequestType.ALLREDUCE,
                  tensor_type=DataType.FLOAT32, tensor_name="grad/conv1",
                  root_rank=-1, device=2, tensor_shape=(32, 64, 3),
                  prescale_factor=0.5, postscale_factor=2.0)
    rl = RequestList([req], shutdown=False)
    out = wire.parse_request_list(wire.serialize_request_list(rl))
    assert out == rl
    assert out.requests[0].tensor_shape == (32, 64, 3)


def test_request_list_shutdown_bit():
    rl = RequestList([], shutdown=True)
    out = wire.parse_request_list(wire.serialize_request_list(rl))
    assert out.shutdown is True
    assert out.requests == []


def test_many_requests_roundtrip():
    reqs = [
        Request(request_rank=r, request_type=t, tensor_type=dt,
                tensor_name=f"t{r}.{int(t)}.{int(dt)}",
                tensor_shape=(r + 1, 7), root_rank=r % 2, device=-1)
        for r in range(5)
        for t in (RequestType.ALLREDUCE, RequestType.ALLGATHER,
                  RequestType.BROADCAST)
        for dt in (DataType.FLOAT32, DataType.BFLOAT16, DataType.INT64)
    ]
    rl = RequestList(reqs)
    out = wire.parse_request_list(wire.serialize_request_list(rl))
    assert out == rl


def test_response_roundtrip():
    resp = Response(response_type=ResponseType.ALLREDUCE,
                    tensor_names=["a", "b", "c"],
                    devices=[-1, -1], tensor_sizes=[12, 4, 9],
                    prescale_factor=1.0, postscale_factor=0.25)
    rl = ResponseList([resp], shutdown=False)
    out = wire.parse_response_list(wire.serialize_response_list(rl))
    assert out == rl


def test_error_response_roundtrip():
    resp = Response(response_type=ResponseType.ERROR,
                    tensor_names=["bad"],
                    error_message="Mismatched allreduce tensor shapes: ...")
    rl = ResponseList([resp], shutdown=True)
    out = wire.parse_response_list(wire.serialize_response_list(rl))
    assert out.shutdown
    assert out.responses[0].response_type == ResponseType.ERROR
    assert "Mismatched" in out.responses[0].error_message


def test_unicode_tensor_names():
    req = Request(tensor_name="层/グラデーション∇", tensor_shape=(1,))
    rl = RequestList([req])
    out = wire.parse_request_list(wire.serialize_request_list(rl))
    assert out.requests[0].tensor_name == "层/グラデーション∇"
