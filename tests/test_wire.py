"""Wire protocol round-trip tests (reference analog: the FlatBuffers
encode/decode paths in horovod/common/message.cc:122-215,317-346)."""

import pytest

from horovod_tpu.common.message import (
    DataType, Request, RequestList, RequestType, Response, ResponseList,
    ResponseType,
)
from horovod_tpu.common import wire


def test_request_roundtrip():
    req = Request(request_rank=3, request_type=RequestType.ALLREDUCE,
                  tensor_type=DataType.FLOAT32, tensor_name="grad/conv1",
                  root_rank=-1, device=2, tensor_shape=(32, 64, 3),
                  prescale_factor=0.5, postscale_factor=2.0)
    rl = RequestList([req], shutdown=False)
    out = wire.parse_request_list(wire.serialize_request_list(rl))
    assert out == rl
    assert out.requests[0].tensor_shape == (32, 64, 3)


def test_request_list_shutdown_bit():
    rl = RequestList([], shutdown=True)
    out = wire.parse_request_list(wire.serialize_request_list(rl))
    assert out.shutdown is True
    assert out.requests == []


def test_many_requests_roundtrip():
    reqs = [
        Request(request_rank=r, request_type=t, tensor_type=dt,
                tensor_name=f"t{r}.{int(t)}.{int(dt)}",
                tensor_shape=(r + 1, 7), root_rank=r % 2, device=-1)
        for r in range(5)
        for t in (RequestType.ALLREDUCE, RequestType.ALLGATHER,
                  RequestType.BROADCAST)
        for dt in (DataType.FLOAT32, DataType.BFLOAT16, DataType.INT64)
    ]
    rl = RequestList(reqs)
    out = wire.parse_request_list(wire.serialize_request_list(rl))
    assert out == rl


def test_response_roundtrip():
    resp = Response(response_type=ResponseType.ALLREDUCE,
                    tensor_names=["a", "b", "c"],
                    devices=[-1, -1], tensor_sizes=[12, 4, 9],
                    prescale_factor=1.0, postscale_factor=0.25)
    rl = ResponseList([resp], shutdown=False)
    out = wire.parse_response_list(wire.serialize_response_list(rl))
    assert out == rl


def test_error_response_roundtrip():
    resp = Response(response_type=ResponseType.ERROR,
                    tensor_names=["bad"],
                    error_message="Mismatched allreduce tensor shapes: ...")
    rl = ResponseList([resp], shutdown=True)
    out = wire.parse_response_list(wire.serialize_response_list(rl))
    assert out.shutdown
    assert out.responses[0].response_type == ResponseType.ERROR
    assert "Mismatched" in out.responses[0].error_message


def test_unicode_tensor_names():
    req = Request(tensor_name="层/グラデーション∇", tensor_shape=(1,))
    rl = RequestList([req])
    out = wire.parse_request_list(wire.serialize_request_list(rl))
    assert out.requests[0].tensor_name == "层/グラデーション∇"


def test_randomized_roundtrips():
    """Seeded fuzz over the codec: arbitrary ranks/dtypes/shapes/
    scales/unicode names must survive serialize -> parse exactly."""
    import numpy as np
    from horovod_tpu.common.message import (
        DataType, Request, RequestList, RequestType, Response,
        ResponseList, ResponseType,
    )
    from horovod_tpu.common import wire

    rng = np.random.RandomState(7)
    req_types = [RequestType.ALLREDUCE, RequestType.ALLGATHER,
                 RequestType.BROADCAST, RequestType.ALLTOALL,
                 RequestType.REDUCESCATTER, RequestType.BARRIER]
    dtypes = list(DataType)
    names = ["t", "grad/層/0", "a.b-c_d", "🙂/émoji", "x" * 200]
    for _ in range(60):
        reqs = [Request(
            request_rank=int(rng.randint(0, 1 << 20)),
            request_type=req_types[rng.randint(len(req_types))],
            tensor_type=dtypes[rng.randint(len(dtypes))],
            tensor_name=names[rng.randint(len(names))]
            + str(rng.randint(1000)),
            root_rank=int(rng.randint(-1, 64)),
            device=int(rng.randint(-1, 8)),
            tensor_shape=[int(s) for s in
                          rng.randint(0, 1 << 16,
                                      size=rng.randint(0, 6))],
            prescale_factor=float(rng.randn()),
            postscale_factor=float(rng.randn()),
        ) for _ in range(rng.randint(0, 8))]
        rl = RequestList(reqs, shutdown=bool(rng.randint(2)))
        assert wire.parse_request_list(
            wire.serialize_request_list(rl)) == rl

        resps = [Response(
            response_type=ResponseType(
                [ResponseType.ALLREDUCE, ResponseType.ALLGATHER,
                 ResponseType.BROADCAST, ResponseType.ERROR][
                     rng.randint(4)]),
            tensor_names=[f"n{j}.{rng.randint(100)}"
                          for j in range(rng.randint(0, 5))],
            error_message="e" * rng.randint(0, 50),
            devices=[int(d) for d in
                     rng.randint(0, 8, size=rng.randint(0, 4))],
            tensor_sizes=[int(s) for s in
                          rng.randint(0, 1 << 30,
                                      size=rng.randint(0, 4))],
            prescale_factor=float(rng.randn()),
            postscale_factor=float(rng.randn()),
        ) for _ in range(rng.randint(0, 5))]
        rsl = ResponseList(resps, shutdown=bool(rng.randint(2)),
                           tuned_cycle_time_ms=float(abs(rng.randn())),
                           tuned_fusion_threshold_bytes=int(
                               rng.randint(0, 1 << 26)))
        assert wire.parse_response_list(
            wire.serialize_response_list(rsl)) == rsl
