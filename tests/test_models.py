"""Model zoo shape/grad sanity (fp32 on CPU devices)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from horovod_tpu.models import (
    MnistConvNet, ResNet18, TransformerConfig, TransformerLM,
)
from horovod_tpu.models.transformer import causal_attention, lm_loss


def test_mnist_convnet_forward():
    model = MnistConvNet()
    x = jnp.zeros((2, 28, 28, 1))
    params = model.init(jax.random.key(0), x)
    out = model.apply(params, x)
    assert out.shape == (2, 10)


def test_resnet18_forward_train_eval():
    model = ResNet18(num_classes=10, dtype=jnp.float32)
    x = jnp.zeros((2, 32, 32, 3))
    variables = model.init(jax.random.key(0), x, train=False)
    out = model.apply(variables, x, train=False)
    assert out.shape == (2, 10)
    out, updates = model.apply(variables, x, train=True,
                               mutable=["batch_stats"])
    assert out.shape == (2, 10)
    assert "batch_stats" in updates


def test_transformer_forward_and_loss_grad():
    cfg = TransformerConfig(vocab_size=128, num_layers=2, num_heads=2,
                            head_dim=8, max_seq_len=16, dtype=jnp.float32)
    model = TransformerLM(cfg)
    tokens = jnp.zeros((2, 16), jnp.int32)
    params = model.init(jax.random.key(0), tokens)
    logits = model.apply(params, tokens)
    assert logits.shape == (2, 16, 128)
    assert logits.dtype == jnp.float32

    def loss(p):
        return lm_loss(model.apply(p, tokens), tokens)

    g = jax.grad(loss)(params)
    flat = jax.tree_util.tree_leaves(g)
    assert all(np.isfinite(np.asarray(t)).all() for t in flat)


def test_causal_attention_masks_future():
    b, s, h, d = 1, 6, 2, 4
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    out1 = causal_attention(q, k, v)
    # Perturbing future keys/values must not change earlier outputs.
    k2 = k.at[:, -1].set(100.0)
    v2 = v.at[:, -1].set(100.0)
    out2 = causal_attention(q, k2, v2)
    np.testing.assert_allclose(np.asarray(out1[:, :-1]),
                               np.asarray(out2[:, :-1]), atol=1e-5)
    assert not np.allclose(np.asarray(out1[:, -1]), np.asarray(out2[:, -1]))
