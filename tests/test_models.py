"""Model zoo shape/grad sanity (fp32 on CPU devices)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from horovod_tpu.models import (
    MnistConvNet, ResNet18, TransformerConfig, TransformerLM,
)
from horovod_tpu.models.transformer import causal_attention, lm_loss


def test_mnist_convnet_forward():
    model = MnistConvNet()
    x = jnp.zeros((2, 28, 28, 1))
    params = model.init(jax.random.key(0), x)
    out = model.apply(params, x)
    assert out.shape == (2, 10)


def test_resnet18_forward_train_eval():
    model = ResNet18(num_classes=10, dtype=jnp.float32)
    x = jnp.zeros((2, 32, 32, 3))
    variables = model.init(jax.random.key(0), x, train=False)
    out = model.apply(variables, x, train=False)
    assert out.shape == (2, 10)
    out, updates = model.apply(variables, x, train=True,
                               mutable=["batch_stats"])
    assert out.shape == (2, 10)
    assert "batch_stats" in updates


def test_transformer_forward_and_loss_grad():
    cfg = TransformerConfig(vocab_size=128, num_layers=2, num_heads=2,
                            head_dim=8, max_seq_len=16, dtype=jnp.float32)
    model = TransformerLM(cfg)
    tokens = jnp.zeros((2, 16), jnp.int32)
    params = model.init(jax.random.key(0), tokens)
    logits = model.apply(params, tokens)
    assert logits.shape == (2, 16, 128)
    assert logits.dtype == jnp.float32

    def loss(p):
        return lm_loss(model.apply(p, tokens), tokens)

    g = jax.grad(loss)(params)
    flat = jax.tree_util.tree_leaves(g)
    assert all(np.isfinite(np.asarray(t)).all() for t in flat)


def test_causal_attention_masks_future():
    b, s, h, d = 1, 6, 2, 4
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    out1 = causal_attention(q, k, v)
    # Perturbing future keys/values must not change earlier outputs.
    k2 = k.at[:, -1].set(100.0)
    v2 = v.at[:, -1].set(100.0)
    out2 = causal_attention(q, k2, v2)
    np.testing.assert_allclose(np.asarray(out1[:, :-1]),
                               np.asarray(out2[:, :-1]), atol=1e-5)
    assert not np.allclose(np.asarray(out1[:, -1]), np.asarray(out2[:, -1]))


def test_vit_forward_and_trains():
    """ViT: patchify shape math, finite loss, and a few improving
    data-parallel steps on the 8-device mesh with fsdp sharding (the
    generic largest-free-dim rule must handle ViT params unmodified)."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    import optax
    from horovod_tpu import spmd
    from horovod_tpu.models import ViT, ViTConfig
    from horovod_tpu.parallel import fsdp_sharding

    cfg = ViTConfig(image_size=32, patch_size=8, num_classes=10,
                    embed_dim=64, num_layers=2, num_heads=4,
                    dtype=jnp.float32)
    model = ViT(cfg)
    mesh = spmd.create_mesh({"data": 8})
    rng = np.random.RandomState(0)
    x = rng.randn(16, 32, 32, 3).astype(np.float32)
    y = rng.randint(0, 10, 16)

    params = jax.jit(model.init)(jax.random.key(0), jnp.asarray(x[:1]))
    logits = jax.jit(model.apply)(params, jnp.asarray(x[:2]))
    assert logits.shape == (2, 10) and np.isfinite(np.asarray(logits)).all()

    # fsdp shardings apply generically (big matrices pick up the axis)
    sh = fsdp_sharding(params, mesh, axis="data")
    specs = [s.spec for s in jax.tree_util.tree_leaves(
        sh, is_leaf=lambda s: hasattr(s, "spec"))]
    assert any("data" in str(s) for s in specs)
    params = jax.tree_util.tree_map(jax.device_put, params, sh)

    tx = optax.adam(1e-3)
    opt_state = jax.jit(tx.init)(params)

    @jax.jit
    def step(p, s, xb, yb):
        def loss_fn(p):
            lg = model.apply(p, xb)
            oh = jax.nn.one_hot(yb, 10)
            return -jnp.mean(jnp.sum(jax.nn.log_softmax(lg) * oh, -1))
        loss, g = jax.value_and_grad(loss_fn)(p)
        u, s = tx.update(g, s, p)
        return optax.apply_updates(p, u), s, loss

    xb = jax.device_put(jnp.asarray(x), spmd.batch_sharding(mesh))
    yb = jax.device_put(jnp.asarray(y), spmd.batch_sharding(mesh))
    losses = []
    for _ in range(6):
        params, opt_state, loss = step(params, opt_state, xb, yb)
        losses.append(float(loss))
    assert losses[-1] < losses[0]
