"""Single-process (size-1) public API tests: lifecycle, sync/async ops,
handles, duplicate-name errors (reference analog: single-process legs of
test/test_torch.py:59-1163 / test_tensorflow.py:63-766)."""

import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu.common.status import HorovodInternalError


class TestBasics:
    def test_init_shutdown(self, hvd_world):
        assert hvd.initialized()
        assert hvd.rank() == 0
        assert hvd.size() == 1
        assert hvd.local_rank() == 0
        assert hvd.local_size() == 1
        assert hvd.cross_rank() == 0
        assert hvd.cross_size() == 1
        assert hvd.is_homogeneous()
        assert hvd.mpi_threads_supported()

    def test_uninitialized_raises(self):
        hvd.shutdown()
        with pytest.raises(ValueError):
            hvd.rank()

    def test_double_init_is_noop(self, hvd_world):
        hvd.init()
        assert hvd.size() == 1


class TestOpsSize1:
    def test_allreduce_average_identity(self, hvd_world):
        x = np.arange(12, dtype=np.float32).reshape(3, 4)
        out = hvd.allreduce(x, average=True)
        np.testing.assert_allclose(out, x)

    def test_allreduce_sum_identity(self, hvd_world):
        x = np.random.randn(5).astype(np.float64)
        out = hvd.allreduce(x, average=False)
        np.testing.assert_allclose(out, x)

    def test_allreduce_prescale(self, hvd_world):
        x = np.ones(4, np.float32)
        out = hvd.allreduce(x, op=hvd.Sum, prescale_factor=2.0)
        np.testing.assert_allclose(out, 2 * x)

    def test_allgather_identity(self, hvd_world):
        x = np.random.randn(6, 2).astype(np.float32)
        np.testing.assert_allclose(hvd.allgather(x), x)

    def test_broadcast_identity(self, hvd_world):
        x = np.random.randn(2, 2)
        np.testing.assert_allclose(hvd.broadcast(x, root_rank=0), x)

    def test_async_poll_synchronize(self, hvd_world):
        x = np.ones(1000, np.float32)
        h = hvd.allreduce_async(x, average=False, name="async_t")
        while not hvd.poll(h):
            pass
        out = hvd.synchronize(h)
        np.testing.assert_allclose(out, x)

    def test_many_tensors_fused(self, hvd_world):
        handles = [hvd.allreduce_async(np.full(10, i, np.float32),
                                       average=False, name=f"fuse/{i}")
                   for i in range(50)]
        for i, h in enumerate(handles):
            np.testing.assert_allclose(hvd.synchronize(h),
                                       np.full(10, i, np.float32))

    def test_duplicate_name_raises(self, hvd_world):
        # (reference: operations.cc:1459-1462 DUPLICATE_NAME_ERROR;
        # test/test_torch.py:356) — two in-flight ops, same name.
        x = np.ones(4, np.float32)
        h1 = hvd.allreduce_async(x, name="dup")
        h2 = hvd.allreduce_async(x, name="dup")
        statuses = []
        for h in (h1, h2):
            try:
                hvd.synchronize(h)
                statuses.append("ok")
            except HorovodInternalError as e:
                statuses.append("err")
                assert "same name" in str(e)
        # The first generally wins, but at minimum exactly one must fail.
        assert statuses.count("err") >= 1

    def test_jax_array_roundtrip(self, hvd_world):
        import jax.numpy as jnp
        x = jnp.arange(8, dtype=jnp.float32)
        out = hvd.allreduce(x, average=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(x))

    def test_bfloat16_allreduce(self, hvd_world):
        import ml_dtypes
        x = np.ones(16, ml_dtypes.bfloat16)
        out = hvd.allreduce(x, average=False)
        assert out.dtype == x.dtype
        np.testing.assert_allclose(np.asarray(out, np.float32), 1.0)

    def test_integer_average_rejected(self, hvd_world):
        # averaging would truncate the 1/size factor to 0 in the tensor
        # dtype — must be a loud error, not silent zeros
        with pytest.raises(ValueError, match="integer"):
            hvd.allreduce(np.arange(4, dtype=np.int64), average=True)
        with pytest.raises(ValueError, match="integer"):
            hvd.allreduce(np.arange(4, dtype=np.int32), op=hvd.Sum,
                          prescale_factor=0.5)

    def test_alltoall_identity(self, hvd_world):
        x = np.arange(6, dtype=np.float32)
        np.testing.assert_allclose(hvd.alltoall(x), x)

    def test_reducescatter_identity(self, hvd_world):
        x = np.arange(6, dtype=np.float32)
        np.testing.assert_allclose(hvd.reducescatter(x), x)


class TestCompression:
    def test_fp16_roundtrip(self):
        from horovod_tpu import Compression
        x = np.random.randn(10).astype(np.float32)
        c, ctx = Compression.fp16.compress(x)
        assert c.dtype == np.float16
        d = Compression.fp16.decompress(c, ctx)
        assert d.dtype == np.float32
        np.testing.assert_allclose(d, x, atol=1e-2)

    def test_bf16_roundtrip(self):
        import ml_dtypes
        from horovod_tpu import Compression
        x = np.random.randn(10).astype(np.float32)
        c, ctx = Compression.bf16.compress(x)
        assert c.dtype == ml_dtypes.bfloat16
        d = Compression.bf16.decompress(c, ctx)
        assert d.dtype == np.float32
        np.testing.assert_allclose(d, x, atol=1e-1)

    def test_none_passthrough(self):
        from horovod_tpu import Compression
        x = np.random.randn(4).astype(np.float32)
        c, ctx = Compression.none.compress(x)
        assert c is x
        assert Compression.none.decompress(c, ctx) is x

    def test_int_not_compressed(self):
        from horovod_tpu import Compression
        x = np.arange(4, dtype=np.int64)
        c, ctx = Compression.fp16.compress(x)
        assert c.dtype == np.int64


class TestIdleBackoff:
    def test_idle_loop_backs_off_and_wakes_on_enqueue(self, monkeypatch):
        """After the grace period the negotiation loop must slow to the
        backoff cap instead of waking every cycle, and an enqueue must
        snap it awake (so submit latency never pays the backoff)."""
        import time
        import horovod_tpu as hvd
        from horovod_tpu.common import basics as _b
        hvd.shutdown()
        monkeypatch.setenv("HOROVOD_CYCLE_TIME", "1")
        monkeypatch.setenv("HOROVOD_TPU_IDLE_BACKOFF", "25")
        hvd.init()
        try:
            rt = _b.runtime()
            time.sleep(0.3)  # pass the grace period
            c0 = rt._cycle_count
            time.sleep(0.5)
            idle_rate = rt._cycle_count - c0
            # 1 ms cycles would be ~500; the 25 ms cap bounds it to ~20
            assert idle_rate < 120, idle_rate
            # wake-on-enqueue: completion far faster than the backoff
            # window would allow if the loop stayed asleep
            t0 = time.monotonic()
            out = hvd.allreduce(np.ones(4, np.float32), average=False,
                                name="wake.test")
            latency = time.monotonic() - t0
            np.testing.assert_allclose(out, 1.0)
            assert latency < 1.0, latency
        finally:
            hvd.shutdown()

    def test_backoff_disabled_keeps_full_cycle_rate(self, monkeypatch):
        """Relative comparison (same process, back to back) so host
        slowness cancels out: the backoff-off loop must cycle several
        times faster than the backed-off loop."""
        import time
        import horovod_tpu as hvd
        from horovod_tpu.common import basics as _b

        def idle_rate(backoff_ms):
            hvd.shutdown()
            monkeypatch.setenv("HOROVOD_CYCLE_TIME", "1")
            monkeypatch.setenv("HOROVOD_TPU_IDLE_BACKOFF",
                               str(backoff_ms))
            hvd.init()
            try:
                rt = _b.runtime()
                time.sleep(0.3)  # pass the grace period
                c0 = rt._cycle_count
                t0 = time.monotonic()
                time.sleep(0.5)
                return (rt._cycle_count - c0) / (time.monotonic() - t0)
            finally:
                hvd.shutdown()

        rate_off = idle_rate(0)
        rate_on = idle_rate(25)
        assert rate_off > 3 * rate_on, (rate_off, rate_on)


class TestConfigValidation:
    def test_xla_bcast_rendering_validated(self, monkeypatch):
        """A typo'd HOROVOD_XLA_BCAST must raise, not silently pick a
        rendering — per-rank divergence would compile mismatched
        collectives for the same negotiated broadcast."""
        import pytest
        from horovod_tpu.common.config import Config

        monkeypatch.setenv("HOROVOD_XLA_BCAST", "Tree")
        assert Config.from_env().xla_broadcast == "tree"  # case-folded
        monkeypatch.setenv("HOROVOD_XLA_BCAST", "ppermute")
        with pytest.raises(ValueError, match="HOROVOD_XLA_BCAST"):
            Config.from_env()


class TestRaggedPsumDecision:
    """Skew guard for the fused variable-dim0 allgather on the XLA
    plane (reference behavior target: MPI_Allgatherv moves true bytes,
    mpi_operations.cc:95-173)."""

    def test_heavy_skew_picks_psum(self):
        from horovod_tpu.ops.xla_ops import ragged_psum_wins
        # 1 rank with 64 rows, 7 with 1: padded = 8*64, psum = 2*(71+64)
        sizes = [64, 1, 1, 1, 1, 1, 1, 1]
        assert ragged_psum_wins(sizes, [1], 8)

    def test_uniform_keeps_padded_gather(self):
        from horovod_tpu.ops.xla_ops import ragged_psum_wins
        assert not ragged_psum_wins([4] * 8, [1], 8)
        # mild skew below the ~2x-mean crossover
        assert not ragged_psum_wins([6, 4, 4, 4, 4, 4, 4, 4], [1], 8)

    def test_two_rank_world_never_psum(self):
        from horovod_tpu.ops.xla_ops import ragged_psum_wins
        # psum's 2x true bytes can't beat 2 x max at N=2
        assert not ragged_psum_wins([1024, 1], [8], 2)
        assert not ragged_psum_wins([4, 4], [8], 1)

    def test_fused_batch_accounts_all_entries(self):
        from horovod_tpu.ops.xla_ops import ragged_psum_wins
        # entry 0 skewed, entry 1 uniform and large: batch-level byte
        # totals decide (uniform bulk outweighs the skewed entry)
        sizes = [64, 1, 1, 1] + [256, 256, 256, 256]
        assert not ragged_psum_wins(sizes, [1, 64], 4)
