"""Timeline integration tests: run collectives with ``HOROVOD_TIMELINE``
set and verify the Chrome-tracing artifact, mirroring the reference's
grep-the-JSON strategy (reference: test/test_timeline.py:42-58 asserts
NEGOTIATE_ALLREDUCE / ALLREDUCE / CYCLE_START appear after an allreduce
with the env var set)."""

import json
import os

import numpy as np

from tests.test_multiprocess import run_scenario


def _load_events(path):
    with open(path) as f:
        events = json.load(f)  # must be valid JSON after shutdown
    assert isinstance(events, list) and events
    return events


def _assert_vocabulary(events, expect_ranks):
    names = [e.get("name") for e in events]
    phases = {e.get("name"): e.get("ph") for e in events}
    # negotiation spans per op type
    assert "NEGOTIATE_ALLREDUCE" in names
    assert "NEGOTIATE_ALLGATHER" in names
    assert "NEGOTIATE_BROADCAST" in names
    assert phases["NEGOTIATE_ALLREDUCE"] == "B"
    # per-rank readiness ticks (instant events named after the rank)
    tick_names = {e["name"] for e in events
                  if e.get("ph") == "X" and e.get("dur") == 0}
    for r in range(expect_ranks):
        assert str(r) in tick_names, (r, tick_names)
    # top-level execution spans + nested activities
    assert "ALLREDUCE" in names
    assert "ALLGATHER" in names
    assert "BROADCAST" in names
    assert "QUEUE" in names
    assert "COLLECTIVE" in names
    # cycle markers (HOROVOD_TIMELINE_MARK_CYCLES)
    cycle = [e for e in events if e.get("name") == "CYCLE_START"]
    assert cycle and all(e["ph"] == "i" for e in cycle)
    if expect_ranks > 1:
        # fused batches wrap their pack/unpack in memcpy activities
        # (reference: mpi_operations.cc:35-62); the scenario's grouped
        # allreduce guarantees one fused multi-entry batch
        assert "MEMCPY_IN_FUSION_BUFFER" in names, \
            sorted(set(n for n in names if n and "MEMCPY" in n))
        assert "MEMCPY_OUT_FUSION_BUFFER" in names
    # per-tensor trace processes carry the tensor names
    proc_names = {e["args"]["name"] for e in events
                  if e.get("name") == "process_name"}
    assert any(n.startswith("tl.") for n in proc_names), proc_names


def test_timeline_single_process(tmp_path, monkeypatch):
    import horovod_tpu as hvd
    hvd.shutdown()  # drop any world a prior test left behind
    path = str(tmp_path / "timeline.json")
    monkeypatch.setenv("HOROVOD_TIMELINE", path)
    monkeypatch.setenv("HOROVOD_TIMELINE_MARK_CYCLES", "1")
    hvd.init()
    try:
        x = np.ones(64, np.float32)
        np.testing.assert_allclose(
            hvd.allreduce(x, average=False, name="tl.ar"), x)
        hvd.allgather(x, name="tl.ag")
        hvd.broadcast(x, root_rank=0, name="tl.bc")
    finally:
        hvd.shutdown()
    _assert_vocabulary(_load_events(path), expect_ranks=1)


def test_timeline_two_process(tmp_path):
    path = str(tmp_path / "timeline_mp.json")
    run_scenario("timeline", 2,
                 extra_env={"HOROVOD_TIMELINE": path,
                            "HOROVOD_TIMELINE_MARK_CYCLES": "1"})
    events = _load_events(path)
    _assert_vocabulary(events, expect_ranks=2)
    # negotiation must have waited for BOTH ranks on some tensor: a
    # NEGOTIATE span containing ticks for ranks 0 and 1
    assert {e["name"] for e in events
            if e.get("ph") == "X"} >= {"0", "1"}


def test_timeline_spans_carry_world_cycle(tmp_path):
    """Every span-opening event (B/X/i/b) carries the world-identical
    cycle sequence number in args.wc (ISSUE 11), monotone
    non-decreasing in emit order — so two per-rank timeline files (or
    a timeline and the merged world trace) correlate by eye without
    the aggregator armed."""
    path = str(tmp_path / "timeline_wc.json")
    run_scenario("timeline", 2,
                 extra_env={"HOROVOD_TIMELINE": path,
                            "HOROVOD_TIMELINE_MARK_CYCLES": "1"})
    events = _load_events(path)
    opening = [e for e in events
               if e.get("ph") in ("B", "X", "i", "b")]
    assert opening
    wcs = [e["args"]["wc"] for e in opening]
    assert all(isinstance(w, int) for w in wcs)
    # collectives ran, so rounds advanced past zero...
    assert max(wcs) >= 2
    # ...monotonically in emit order (the background thread emits and
    # bumps in one place; writer order is queue order)
    assert wcs == sorted(wcs)
    # closing events stay unstamped (viewers inherit from the opener)
    assert all("wc" not in (e.get("args") or {}) for e in events
               if e.get("ph") in ("E", "e"))


def test_timeline_cached_negotiation_markers(tmp_path):
    """Hit cycles carry no per-tensor NEGOTIATE spans, so the trace's
    evidence of the fast path is the instant NEGOTIATE_CACHED marker —
    and NEGOTIATE_CACHED_FUSED when the cycle also carried the fused
    data (docs/performance.md)."""
    # classic bitmask cycles (shm data plane -> no speculation)
    p1 = str(tmp_path / "tl_cached.json")
    run_scenario("response_cache_steady", 2, timeout=120.0,
                 extra_env={"HOROVOD_TIMELINE": p1})
    names = {e.get("name") for e in _load_events(p1)}
    assert "NEGOTIATE_CACHED" in names, sorted(
        n for n in names if n and "NEGOT" in n)
    # fused speculative cycles (socket star data plane)
    p2 = str(tmp_path / "tl_spec.json")
    run_scenario("response_cache_steady", 2, timeout=120.0,
                 extra_env={"HOROVOD_TIMELINE": p2,
                            "HOROVOD_TPU_SHM": "0"})
    names = {e.get("name") for e in _load_events(p2)}
    assert "NEGOTIATE_CACHED_FUSED" in names, sorted(
        n for n in names if n and "NEGOT" in n)


def test_timeline_flushed_on_world_abort(tmp_path, monkeypatch):
    """Abort-path flush regression: a WorldAbortedError teardown —
    even one where the finalizer drain AND a user completion callback
    raise — must still close the timeline's JSON array. The aborted
    runs are exactly the traces you most want to inspect."""
    import horovod_tpu as hvd
    from horovod_tpu.common import basics as _b
    from horovod_tpu.common.message import Request
    from horovod_tpu.common.status import (
        WorldAbortedError, world_abort_message,
    )
    from horovod_tpu.common.tensor_table import TensorTableEntry

    hvd.shutdown()
    path = str(tmp_path / "tl_abort.json")
    monkeypatch.setenv("HOROVOD_TIMELINE", path)
    hvd.init()
    try:
        rt = _b.runtime()
        x = np.ones(8, np.float32)
        np.testing.assert_allclose(
            hvd.allreduce(x, average=False, name="ab.ar"), x)

        # hostile teardown: a raising finalizer drain and a pending
        # entry whose completion callback raises
        if rt.finalizer is not None:
            def _bad_drain():
                raise RuntimeError("drain boom")
            rt.finalizer.drain = _bad_drain

        def _bad_cb(status):
            raise RuntimeError("user callback boom")
        rt.tensor_table.add(
            TensorTableEntry("ab.pending", x, callback=_bad_cb),
            Request(tensor_name="ab.pending"))

        def _abort(payload):
            raise WorldAbortedError(
                world_abort_message(0, "injected test abort"),
                origin_rank=0, cause="injected test abort")
        rt.controller.gather_requests = _abort
        rt._wake.set()
        rt.join(timeout=20.0)
        assert rt._done.is_set()
        assert isinstance(rt._error, WorldAbortedError)
    finally:
        hvd.shutdown()
    events = _load_events(path)  # valid JSON: the array was closed
    assert any(e.get("name") == "ALLREDUCE" for e in events)


def test_timeline_flushed_on_sigkill_abort(tmp_path):
    """End-to-end: rank 1 of 3 is SIGKILL'd mid-collective; rank 0's
    timeline must still be a terminated, loadable trace after its
    WorldAbortedError teardown."""
    import signal
    path = str(tmp_path / "tl_sigkill.json")
    run_scenario(
        "abort_sigkill_leaf", 3, timeout=60.0,
        extra_env={"HOROVOD_TIMELINE": path,
                   "HOROVOD_HEARTBEAT_INTERVAL": "0.3",
                   "HOROVOD_HEARTBEAT_TIMEOUT": "3",
                   "HOROVOD_FAULT_SPEC": "rank=1:kill:op=3"},
        expect_rc={1: -signal.SIGKILL})
    events = _load_events(path)
    assert any(e.get("name") == "ALLREDUCE" for e in events)


def test_timeline_writer_queue_bounded(tmp_path):
    """A wedged writer (hung disk) must not grow the queue without
    limit: events past the cap are dropped and counted, the dropped
    count feeds an attached metrics counter, and the trace still
    terminates as valid JSON once the writer recovers."""
    import threading

    from horovod_tpu.common.metrics import MetricsRegistry
    from horovod_tpu.common.timeline import Timeline

    gate = threading.Event()
    orig_loop = Timeline._write_loop

    def stalled_loop(self):
        gate.wait()
        orig_loop(self)

    path = str(tmp_path / "tl_bounded.json")
    Timeline._write_loop = stalled_loop
    try:
        tl = Timeline(path, queue_capacity=8)
        counter = MetricsRegistry().counter(
            "hvd_timeline_dropped_events_total")
        tl.attach_drop_counter(counter)
        for i in range(100):
            tl.start(f"t{i}", "ALLREDUCE")
            tl.end(f"t{i}")
        assert tl.dropped_events > 0
        assert tl._queue.qsize() <= 8
        assert counter.value == tl.dropped_events
        gate.set()
        tl.shutdown()
    finally:
        Timeline._write_loop = orig_loop
    events = _load_events(path)  # lossy but valid + terminated
    assert len(events) <= 9


def test_timeline_off_by_default(tmp_path, monkeypatch):
    import horovod_tpu as hvd
    hvd.shutdown()
    monkeypatch.delenv("HOROVOD_TIMELINE", raising=False)
    hvd.init()
    try:
        from horovod_tpu.common import basics as _b
        assert not _b.runtime().timeline.enabled
    finally:
        hvd.shutdown()
