"""Wire-dtype gradient compression + two-level collectives (ISSUE 9).

Three tiers in one module:

* unit tests of the shared dtype table / wire codec / negotiation
  resolution / per-bucket autotuner grid (common/wire_dtype.py,
  coordinator.py, parameter_manager.py);
* byte-layout parity of the compressed steady plan against the Python
  serializer (the native/pure-Python interop contract);
* multi-process legs: compressed zero-copy steady state, heterogeneous
  knob negotiation (bit-exact vs a fresh all-none replay), two-level
  multi-host allreduce, SIGKILL mid-compressed-cycle fail-fast, and
  the convergence-parity training runs (none vs bf16 vs int8+EF).
"""

import json
import os
import signal

import numpy as np
import pytest

from horovod_tpu.common import wire as hwire
from horovod_tpu.common import wire_dtype as wd
from horovod_tpu.common.compression import Compression
from horovod_tpu.common.coordinator import (
    ResponseCache, construct_response, fuse_responses, MessageTable,
)
from horovod_tpu.common.message import (
    DataType, Request, RequestList, RequestType, Response, ResponseType,
)
from tests.test_multiprocess import run_scenario

_HB_ENV = {
    "HOROVOD_HEARTBEAT_INTERVAL": "0.3",
    "HOROVOD_HEARTBEAT_TIMEOUT": "3",
}
_SIGKILL_RC = -signal.SIGKILL
_SOCKET_ENV = {"HOROVOD_TPU_SHM": "0", "HOROVOD_TPU_RING_THRESHOLD": "-1"}


# -- shared dtype table (the satellite bugfix) ------------------------------

class TestSharedDtypeTable:
    def test_wire_codec_and_compression_agree_on_bfloat16(self):
        """The bug class this PR closes: compression.py's old local
        name list vs the wire codec's — ml_dtypes/jax bfloat16 must be
        floating to BOTH, via ONE table."""
        import ml_dtypes
        from horovod_tpu.common.compression import _is_floating

        class T:
            dtype = np.dtype(ml_dtypes.bfloat16)

        assert _is_floating(T())
        assert wd.is_floating(np.dtype(ml_dtypes.bfloat16))
        assert wd.is_floating(np.float32)
        assert not wd.is_floating(np.int32)

    def test_framework_cast_is_noop_while_wire_active(self):
        """Double-cast deprecation: with wire compression active the
        framework-level Compression helpers pass through."""
        x = np.ones(8, np.float32)
        wd.set_active(wd.WIRE_BF16)
        try:
            out, ctx = Compression.bf16.compress(x)
            assert out is x and ctx is None
            out, ctx = Compression.fp16.compress(x)
            assert out is x and ctx is None
        finally:
            wd.set_active(wd.WIRE_NONE)
        out, ctx = Compression.fp16.compress(x)
        assert out.dtype == np.float16  # inactive: classic cast


# -- codec ------------------------------------------------------------------

class TestCodec:
    def test_wire_code_of(self):
        assert wd.wire_code_of("bf16") == wd.WIRE_BF16
        assert wd.wire_code_of("NONE") == wd.WIRE_NONE
        with pytest.raises(ValueError):
            wd.wire_code_of("bf17")

    def test_config_rejects_typo(self):
        from horovod_tpu.common.config import Config
        os.environ["HOROVOD_COMPRESSION"] = "b16"
        try:
            with pytest.raises(ValueError):
                Config.from_env()
        finally:
            del os.environ["HOROVOD_COMPRESSION"]

    def test_resolve_common_denominator(self):
        assert wd.resolve([wd.WIRE_BF16, wd.WIRE_NONE]) == wd.WIRE_NONE
        assert wd.resolve([wd.WIRE_INT8, wd.WIRE_BF16]) == wd.WIRE_BF16
        assert wd.resolve([wd.WIRE_FP16, wd.WIRE_FP16]) == wd.WIRE_FP16
        assert wd.resolve([]) == wd.WIRE_NONE

    @pytest.mark.parametrize("wire,tol", [(wd.WIRE_BF16, 1e-2),
                                          (wd.WIRE_FP16, 1e-3)])
    def test_cast_roundtrip(self, wire, tol):
        a = np.linspace(-3, 3, 1001, dtype=np.float32)
        c = wd.compress(a, wire)
        assert c.nbytes == a.nbytes // 2
        d = wd.decompress(c, wire, np.float32, a.size)
        assert d.dtype == np.float32 and d.flags.writeable
        np.testing.assert_allclose(d, a, atol=tol)
        # bytes input (the recv path) decodes identically
        d2 = wd.decompress(bytes(memoryview(c.view(np.uint8))), wire,
                           np.float32, a.size)
        np.testing.assert_array_equal(d, d2)

    def test_int8_roundtrip_and_exact_constants(self):
        a = np.linspace(-3, 3, 1001, dtype=np.float32)
        q = wd.quantize(a)
        assert q.nbytes == a.size + 4
        d = wd.dequantize(q, np.float32, a.size)
        # quantization granularity: half a lane of max|x|/127
        np.testing.assert_allclose(d, a, atol=3.0 / 127.0 * 0.51)
        # constant tensors are exact (q == ±127)
        c = np.full(64, 7.5, np.float32)
        np.testing.assert_array_equal(
            wd.dequantize(wd.quantize(c), np.float32, 64), c)

    def test_error_feedback_bounds_drift(self):
        """DGC property: with residual feedback the ACCUMULATED
        quantized stream tracks the true accumulated gradient."""
        rng = np.random.RandomState(0)
        a = rng.randn(512).astype(np.float32)
        ef = wd.ErrorFeedback()
        acc = np.zeros_like(a)
        for _ in range(50):
            comp = ef.apply(("k",), a)
            q = wd.quantize(comp)
            ef.update(("k",), comp, q)
            acc += wd.dequantize(q, np.float32, a.size)
        drift = np.abs(acc - 50 * a).max()
        # without EF the drift would be ~50 * scale/2 ≈ 25 lanes; with
        # it, at most ~1 lane of the running residual
        assert drift <= 2 * np.abs(a).max() / 127.0, drift

    def test_error_feedback_lru_keeps_hot_keys_past_cap(self):
        """More distinct batches than the cap must evict the OLDEST
        residual, never wipe the store — a hot key's compensation
        chain survives arbitrary cold-key churn."""
        ef = wd.ErrorFeedback()
        hot = np.full(16, 0.3, np.float32)
        for i in range(3 * ef._CAP):
            comp = ef.apply(("hot",), hot)
            q = wd.quantize(comp)
            ef.update(("hot",), comp, q)
            cold = np.full(16, float(i + 1), np.float32)
            ccomp = ef.apply((f"cold{i}",), cold)
            ef.update((f"cold{i}",), ccomp, wd.quantize(ccomp))
            assert ("hot",) in ef._residuals, i
            assert len(ef._residuals) <= ef._CAP

    def test_reduce_wire_bf16_matches_sequential_sum(self):
        rng = np.random.RandomState(1)
        parts = [rng.randn(256).astype(np.float32) for _ in range(4)]
        wires = [wd.compress(p, wd.WIRE_BF16) for p in parts]
        acc = np.array(wires[0], copy=True)
        out = wd.reduce_wire(acc, wires[1:], wd.WIRE_BF16,
                             np.float32, 256)
        ref = wires[0].astype(np.float32)
        for w in wires[1:]:
            ref = (ref + w.astype(np.float32)).astype(
                wires[0].dtype).astype(np.float32)
        np.testing.assert_allclose(out.astype(np.float32), ref)

    def test_reduce_wire_int8_requantizes_world_sum(self):
        rng = np.random.RandomState(2)
        parts = [rng.randn(256).astype(np.float32) for _ in range(4)]
        bufs = [wd.quantize(p) for p in parts]
        out = wd.reduce_wire(bufs[0], bufs[1:], wd.WIRE_INT8,
                             np.float32, 256)
        got = wd.dequantize(out, np.float32, 256)
        want = sum(wd.dequantize(b, np.float32, 256) for b in bufs)
        np.testing.assert_allclose(got, want,
                                   atol=np.abs(want).max() / 127.0)

    def test_native_cast_matches_numpy_round_to_nearest_even(self):
        from horovod_tpu import native
        if native.get() is None or not hasattr(native.get(),
                                               "hvd_cast"):
            pytest.skip("native core unavailable")
        import ml_dtypes
        rng = np.random.RandomState(3)
        a = rng.randn(4096).astype(np.float32)
        b = np.empty(4096, ml_dtypes.bfloat16)
        assert native.cast_into(a, b)
        np.testing.assert_array_equal(
            b.view(np.uint16), a.astype(ml_dtypes.bfloat16).view(
                np.uint16))
        h = np.empty(4096, np.float16)
        assert native.cast_into(a, h)
        np.testing.assert_array_equal(h, a.astype(np.float16))


# -- negotiation ------------------------------------------------------------

def _req(rank, wire, name="t", dtype=DataType.FLOAT32, shape=(8,)):
    return Request(request_rank=rank, request_type=RequestType.ALLREDUCE,
                   tensor_type=dtype, tensor_name=name,
                   tensor_shape=shape, wire_dtype=wire)


class TestNegotiation:
    def test_construct_response_resolves_min(self):
        table = MessageTable()
        for r, w in enumerate((wd.WIRE_INT8, wd.WIRE_BF16,
                               wd.WIRE_INT8)):
            table.increment_tensor_count(_req(r, w), 3)
        resp = construct_response(table, "t", 3)
        assert resp.wire_dtype == wd.WIRE_BF16

    def test_one_rank_uncompressed_degrades_batch(self):
        table = MessageTable()
        for r, w in enumerate((wd.WIRE_BF16, wd.WIRE_NONE,
                               wd.WIRE_BF16)):
            table.increment_tensor_count(_req(r, w), 3)
        assert construct_response(table, "t", 3).wire_dtype \
            == wd.WIRE_NONE

    def test_incompressible_dtype_never_compresses(self):
        table = MessageTable()
        for r in range(2):
            table.increment_tensor_count(
                _req(r, wd.WIRE_BF16, dtype=DataType.INT32), 2)
        assert construct_response(table, "t", 2).wire_dtype \
            == wd.WIRE_NONE

    def test_wire_rides_request_and_response_codec(self):
        req = _req(1, wd.WIRE_INT8)
        rl = hwire.parse_request_list(
            hwire.serialize_request_list(RequestList([req])))
        assert rl.requests[0].wire_dtype == wd.WIRE_INT8
        resp = Response(response_type=ResponseType.ALLREDUCE,
                        tensor_names=["t"], tensor_sizes=[8],
                        wire_dtype=wd.WIRE_BF16,
                        algorithm=wd.ALG_TWOLEVEL)
        from horovod_tpu.common.message import ResponseList
        out = hwire.parse_response_list(
            hwire.serialize_response_list(ResponseList([resp])))
        assert out.responses[0].wire_dtype == wd.WIRE_BF16
        assert out.responses[0].algorithm == wd.ALG_TWOLEVEL

    def test_cache_signature_includes_wire_dtype(self):
        """A knob change must renegotiate, not replay a stale
        compression verdict."""
        cache = ResponseCache(8)
        req = _req(0, wd.WIRE_BF16)
        cache.put("t", ResponseCache.signature(req),
                  Response(response_type=ResponseType.ALLREDUCE,
                           tensor_names=["t"], tensor_sizes=[8]),
                  DataType.FLOAT32, 1)
        state, _ = cache.lookup(req)
        assert state == ResponseCache.HIT
        state, _ = cache.lookup(_req(0, wd.WIRE_NONE))
        assert state == ResponseCache.INVALID

    def test_fusion_keeps_mixed_verdicts_apart(self):
        def resp(name, wire=0, alg=0):
            return Response(response_type=ResponseType.ALLREDUCE,
                            tensor_names=[name], tensor_sizes=[8],
                            devices=[0, 0], wire_dtype=wire,
                            algorithm=alg)
        dtypes = {n: DataType.FLOAT32 for n in "abcd"}
        fused = fuse_responses(
            [resp("a", wd.WIRE_BF16), resp("b", wd.WIRE_NONE),
             resp("c", wd.WIRE_BF16), resp("d", alg=wd.ALG_TWOLEVEL)],
            dtypes, 1 << 20, {n: 1 for n in "abcd"})
        names = sorted(tuple(f.tensor_names) for f in fused)
        assert ("a", "c") in names      # same verdict fuses
        assert ("b",) in names and ("d",) in names

    def test_static_policy(self):
        p = wd.StaticWirePolicy(True, 1 << 20, multi_host=True)
        assert p.plan(2 << 20) == (wd.ALG_TWOLEVEL, None)
        assert p.plan(4096) == (wd.ALG_DEFAULT, None)
        p2 = wd.StaticWirePolicy(True, 0, multi_host=False)
        assert p2.plan(2 << 20) == (wd.ALG_DEFAULT, None)


# -- per-bucket autotuner grid ----------------------------------------------

class TestBucketTuner:
    def test_converges_to_best_combo_and_skips_idle_buckets(self):
        from horovod_tpu.common.parameter_manager import _BucketTuner
        combos = [(wd.ALG_DEFAULT, wd.WIRE_NONE),
                  (wd.ALG_DEFAULT, wd.WIRE_BF16),
                  (wd.ALG_RING, wd.WIRE_NONE),
                  (wd.ALG_RING, wd.WIRE_BF16),
                  (wd.ALG_TWOLEVEL, wd.WIRE_NONE),
                  (wd.ALG_TWOLEVEL, wd.WIRE_BF16)]
        t = _BucketTuner(combos, 3)
        quality = {(wd.ALG_TWOLEVEL, wd.WIRE_BF16): 4.0,
                   (wd.ALG_RING, wd.WIRE_BF16): 2.0}
        guard = 0
        while not t.done:
            guard += 1
            assert guard < 100
            if t.bucket < 2:
                t.feed(1.0, 0)      # idle bucket: no traffic
            else:
                t.feed(quality.get(t.current_combo(), 1.0), 1 << 20)
        assert t.plan[0] == (wd.ALG_DEFAULT, None)   # idle kept default
        assert t.plan[1] == (wd.ALG_DEFAULT, None)
        assert t.plan[2] == (wd.ALG_TWOLEVEL, wd.WIRE_BF16)

    def test_parameter_manager_grid_then_bayes(self):
        """The grid phase settles the bucket table, then the
        continuous BO phase still converges — tuning ends once."""
        from horovod_tpu.common.config import Config
        from horovod_tpu.common.controller import LocalController
        from horovod_tpu.common.parameter_manager import ParameterManager
        cfg = Config()
        cfg.autotune = True
        cfg.autotune_warmup_samples = 1
        cfg.autotune_steps_per_sample = 2
        cfg.autotune_bayes_opt_max_samples = 3
        pm = ParameterManager(cfg, LocalController())
        pm.configure_wire(wd.WIRE_BF16, multi_host=False, world_size=2)
        # world_size 2 + single host: grid = default x {none, bf16}
        for _ in range(2000):
            pm.plan(2 << 20)
            pm.on_cycle(2 << 20)
            if not pm.tuning:
                break
        assert not pm.tuning
        plan = pm.bucket_plan()
        assert plan[2][0] == wd.ALG_DEFAULT
        assert plan[2][1] in (wd.WIRE_NONE, wd.WIRE_BF16)

    def test_wire_candidates_never_exceed_proposal(self):
        from horovod_tpu.common.config import Config
        from horovod_tpu.common.controller import LocalController
        from horovod_tpu.common.parameter_manager import ParameterManager
        cfg = Config()
        cfg.autotune = True
        pm = ParameterManager(cfg, LocalController())
        pm.configure_wire(wd.WIRE_NONE, multi_host=False, world_size=2)
        # nothing to explore: single combo -> no tuner armed
        assert pm._bucket_tuner is None


# -- compressed steady-plan byte parity -------------------------------------

class TestCompressedSteadyPlan:
    def test_frame_bytes_match_python_serializer(self):
        """The native steady cycle byte-compares frames against
        wire.spec_frame_parts; a COMPRESSED plan must serialize to
        exactly what the Python path would send for the same compressed
        segments — one layout, two implementations."""
        import ml_dtypes
        from horovod_tpu.common.arena import FusionArena
        from horovod_tpu.common.message import CacheCycleRequest
        from horovod_tpu.common.steady import SteadyPlan
        arena = FusionArena()
        rng = np.random.RandomState(7)
        arrays = [rng.randn(64).astype(np.float32),
                  rng.randn(32).astype(np.float32)]
        count = 96
        plan = SteadyPlan(
            epoch=5, nslots=8, mask=0b11,
            segments=[(DataType.BFLOAT16, np.dtype(ml_dtypes.bfloat16),
                       count * 2, np.dtype(np.float32))],
            arena=arena)
        bufs = plan.pack([arrays], [1.0], use_arena=True)
        assert bufs[0].dtype == np.dtype(ml_dtypes.bfloat16)
        frame = plan.frame_bytes(bufs)
        fused = np.concatenate(arrays)
        ref = hwire.serialize_cycle_request(CacheCycleRequest(
            epoch=5, nslots=8, hit_mask=0b11,
            spec_payload=[(DataType.BFLOAT16,
                           fused.astype(ml_dtypes.bfloat16))]))
        assert frame == ref
        # and the segment decompresses back within bf16 tolerance
        got = wd.decompress(bufs[0], wd.WIRE_BF16, np.float32, count)
        np.testing.assert_allclose(got, fused, atol=0.03)

    def test_prescale_applies_before_cast(self):
        import ml_dtypes
        from horovod_tpu.common.arena import FusionArena
        from horovod_tpu.common.steady import SteadyPlan
        arrays = [np.full(16, 3.0, np.float32)]
        plan = SteadyPlan(
            epoch=0, nslots=4, mask=1,
            segments=[(DataType.BFLOAT16, np.dtype(ml_dtypes.bfloat16),
                       32, np.dtype(np.float32))],
            arena=FusionArena())
        bufs = plan.pack([arrays], [0.5], use_arena=False)
        np.testing.assert_allclose(
            bufs[0].astype(np.float32), 1.5)


# -- multi-process legs -----------------------------------------------------

def test_compressed_steady_zero_copy():
    """bf16 wire on the fused speculative / native zero-copy steady
    path at ws=4: exact values, hvd_data_copies_total == 0, wire bytes
    measurably saved (the ISSUE 9 zero-copy-composition contract)."""
    run_scenario(
        "compression_steady_zero_copy", 4, timeout=120.0,
        extra_env={**_SOCKET_ENV,
                   "HOROVOD_COMPRESSION": "bf16",
                   "HOROVOD_TPU_METRICS": "1"})


def test_compression_hetero_negotiates_common_denominator(tmp_path):
    """One rank proposing bf16 in an otherwise-uncompressed world:
    the verdict degrades to none and the run is BIT-EXACT with a
    fresh all-none world replaying the same submissions."""
    mixed = str(tmp_path / "mixed.npy")
    plain = str(tmp_path / "plain.npy")
    run_scenario(
        "compression_hetero", 3, timeout=90.0,
        extra_env={**_SOCKET_ENV, "HOROVOD_TPU_METRICS": "1",
                   "HVD_COMPRESSION_OUT": mixed},
        per_rank_env=lambda rank: (
            {"HOROVOD_COMPRESSION": "bf16"} if rank == 1 else {}))
    run_scenario(
        "compression_hetero", 3, timeout=90.0,
        extra_env={**_SOCKET_ENV, "HOROVOD_TPU_METRICS": "1",
                   "HVD_COMPRESSION_OUT": plain})
    a = np.load(mixed)
    b = np.load(plain)
    np.testing.assert_array_equal(a, b)


def test_twolevel_allreduce_multihost():
    """Two fake hosts x two ranks: HOROVOD_TWO_LEVEL=1 routes
    allreduce through local shm reduce -> roots ring -> local shm
    broadcast, with the cross leg compressed at bf16."""
    run_scenario(
        "twolevel_allreduce", 4, timeout=120.0,
        extra_env={"HOROVOD_TWO_LEVEL": "1",
                   "HOROVOD_COMPRESSION": "bf16",
                   "HOROVOD_TPU_METRICS": "1"},
        per_rank_env=lambda rank: {
            "HOROVOD_HOSTNAME": f"fakehost{rank // 2}"})


def test_abort_sigkill_mid_compressed_cycle():
    """SIGKILL a rank deep in COMPRESSED bitmask steady state: the
    survivors must still raise WorldAbortedError naming the dead rank
    within the heartbeat deadline — the PR 2 fail-fast invariant
    holds with compression engaged (ISSUE 9 acceptance)."""
    run_scenario(
        "abort_sigkill_cached", 3, timeout=60.0,
        extra_env={**_HB_ENV, **_SOCKET_ENV,
                   "HOROVOD_COMPRESSION": "bf16",
                   "HOROVOD_FAULT_SPEC": "rank=1:kill:op=40"},
        expect_rc={1: _SIGKILL_RC})


def _train_world(tmp_path, tag: str, compression: str) -> dict:
    out = str(tmp_path / f"parity_{tag}.json")
    run_scenario(
        "compression_train_parity", 4, timeout=240.0,
        extra_env={"HOROVOD_COMPRESSION": compression,
                   "HVD_COMPRESSION_OUT": out})
    with open(out) as f:
        return json.load(f)


@pytest.mark.slow
def test_convergence_parity_none_bf16_int8(tmp_path):
    """The ISSUE 9 convergence-parity leg: the toy TransformerLM from
    models/ trained data-parallel at ws=4 under none / bf16 /
    int8+error-feedback wire dtypes must land at the same final loss
    within tolerance — compression changes bytes, not training."""
    base = _train_world(tmp_path, "none", "none")
    bf16 = _train_world(tmp_path, "bf16", "bf16")
    int8 = _train_world(tmp_path, "int8", "int8")
    l0 = base["final_loss"]
    assert np.isfinite(l0)
    # training must actually have progressed in every world
    for world in (base, bf16, int8):
        assert world["losses"][-1] < world["losses"][0], world
    assert abs(bf16["final_loss"] - l0) <= 0.05 * abs(l0) + 1e-3, \
        (l0, bf16["final_loss"])
    assert abs(int8["final_loss"] - l0) <= 0.15 * abs(l0) + 1e-3, \
        (l0, int8["final_loss"])
