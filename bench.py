"""Synthetic training benchmarks (driver-run, real TPU).

TPU-native re-founding of the reference's synthetic benchmarks
(reference: examples/pytorch_synthetic_benchmark.py:95-110,
examples/tensorflow_synthetic_benchmark.py; docs/benchmarks.md:12-33),
with THIS framework in the measured loop the way a user would run it:
``horovod_tpu.jax.DistributedOptimizer`` wrapping the optax
transformation inside a shard_map'd train step over the device mesh
(gradient pmean over the data axis), parameters broadcast through the
framework at start, and donated buffers so XLA updates weights in
place.

Two workloads, one JSON line:

1. **ResNet-50** (the reference's own headline): ImageNet-shaped
   synthetic data, SGD-momentum, batch 256. HBM-roofline-bound on
   every TPU generation — its MFU cap is ~33.5% on v5e and the bench
   reports achieved bandwidth + MFU vs that cap (docs/benchmarks.md
   "MFU roofline study").
2. **Transformer-LM** (compute-bound): 12-layer d=2048 735M-param
   causal LM, seq 2048, bf16, pallas flash attention, chunked
   lm-head cross-entropy, SGD-momentum. This is the workload that can
   actually demonstrate framework speed on the MXU — its steady-state
   training MFU is emitted as ``transformer_hvd_train_mfu``.

Baseline: the reference's published example readout is 1656.82 img/s on
16 Pascal GPUs = 103.55 img/s per device (docs/benchmarks.md:29-33).
``vs_baseline`` is img/s-per-chip divided by that number.

The collective-path microbenches (bus bandwidth through the full
negotiate->fuse->execute pipeline, N-process scaling efficiency) live
in benchmarks/collective_bench.py — they need a multi-process CPU
world, not the single real chip this script is given.
"""

from __future__ import annotations

import json
import os

from horovod_tpu.compat import jaxshim

BASELINE_IMG_PER_SEC_PER_DEVICE = 103.55

# Peak dense bf16 FLOPs per chip by TPU generation (public specs).
_PEAK_BF16 = {
    "v4": 275e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v6e": 918e12,
}

# Peak HBM bandwidth per chip (public specs), for the roofline readout.
_PEAK_HBM = {
    "v4": 1228e9,
    "v5e": 819e9,
    "v5p": 2765e9,
    "v6e": 1640e9,
}


def _tpu_gen() -> str:
    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "").lower()
    if gen not in _PEAK_BF16:
        try:
            import jax
            kind = jax.devices()[0].device_kind.lower().replace(" ", "")
            if "v6" in kind:
                gen = "v6e"
            elif "v5p" in kind:
                gen = "v5p"
            elif "v5" in kind or "lite" in kind:
                gen = "v5e"
            else:
                gen = "v4"
        except Exception:
            gen = "v5e"
    return gen


def _peak_flops(n_dev: int) -> float:
    return _PEAK_BF16.get(_tpu_gen(), _PEAK_BF16["v5e"]) * n_dev


def _bench_transformer(n_dev: int) -> dict:
    """Steady-state transformer-LM training MFU with the framework in
    the loop (the compute-bound companion to the ResNet leg). MFU
    convention: model flops = tokens x (6 x matmul-params +
    12 x L x S x d) — the PaLM accounting, full causal square, on the
    same peak-spec basis as the chip's bf16 rating; the causal kernels
    execute ~5% fewer (flops_ratio reports it)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import horovod_tpu.jax as hvd
    from horovod_tpu import spmd
    from horovod_tpu.models.transformer import (
        TransformerConfig, TransformerLM, lm_loss_from_hidden,
    )
    from horovod_tpu.utils.timing import steady_state_sec_per_step

    per_chip_batch = int(os.environ.get("HVD_BENCH_LM_BATCH", "4"))
    seq = int(os.environ.get("HVD_BENCH_LM_SEQ", "2048"))
    batch = per_chip_batch * n_dev
    cfg = TransformerConfig(vocab_size=32000, num_layers=12,
                            num_heads=16, head_dim=128,
                            max_seq_len=seq, dtype=jnp.bfloat16)
    model = TransformerLM(cfg)
    rng = jax.random.key(0)
    tokens = jnp.zeros((batch, seq), jnp.int32)
    mesh = spmd.create_mesh({"data": n_dev})
    if n_dev > 1:
        tokens = jax.device_put(tokens, spmd.batch_sharding(mesh))
    variables = jax.jit(lambda r, t: model.init(r, t))(rng, tokens)
    params = variables["params"]
    n_params = sum(int(np.prod(p.shape))
                   for p in jax.tree_util.tree_leaves(params))
    d = cfg.embed_dim

    tx = hvd.DistributedOptimizer(
        optax.sgd(0.01, momentum=0.9), axis="data")
    opt_state = tx.init(params)
    params = hvd.broadcast_parameters(params, root_rank=0)

    def loss_fn(p, t):
        hidden = model.apply({"params": p}, t, return_hidden=True)
        return lm_loss_from_hidden(hidden, p["lm_head"]["kernel"], t)

    def step(p, os_, t):
        loss, grads = jax.value_and_grad(loss_fn)(p, t)
        updates, new_os = tx.update(grads, os_, p)
        return optax.apply_updates(p, updates), new_os, loss

    from jax.sharding import PartitionSpec as P
    rep = P()
    step = jaxshim.shard_map(step, mesh=mesh, in_specs=(rep, rep, P("data")),
                         out_specs=(rep, rep, rep))
    train = jax.jit(step, donate_argnums=(0, 1)).lower(
        params, opt_state, tokens).compile()
    hw_flops = None
    try:
        ca = train.cost_analysis()
        ca = ca[0] if isinstance(ca, (list, tuple)) else ca
        fl = float(ca["flops"])
        hw_flops = fl if np.isfinite(fl) and fl > 0 else None
    except Exception:
        pass

    st = {"p": params, "os": opt_state}

    def one_step():
        st["p"], st["os"], loss = train(st["p"], st["os"], tokens)
        return loss

    sec = steady_state_sec_per_step(
        one_step, lambda l: float(l), warmup_steps=5, chunks=4,
        chunk_steps=15)
    tokens_per_step = batch * seq
    # matmul params: everything but the embedding table (a gather);
    # the fp32 lm_head IS a matmul and is included in n_params.
    p_mm = n_params - cfg.vocab_size * d
    model_flops = tokens_per_step * (
        6 * p_mm + 12 * cfg.num_layers * seq * d)
    peak = _peak_flops(n_dev)
    out = {
        "config": f"L{cfg.num_layers} d{d} S{seq} B{batch} "
                  f"V{cfg.vocab_size}",
        "n_params_M": round(n_params / 1e6, 1),
        "tokens_per_sec": round(tokens_per_step / sec),
        "sec_per_step": round(sec, 4),
        "mfu": round(model_flops / sec / peak, 4),
    }
    if hw_flops is not None:
        out["hfu"] = round(hw_flops / sec / peak, 4)
        out["flops_ratio_executed_vs_model"] = round(
            hw_flops / model_flops, 3)
    return out


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import horovod_tpu.jax as hvd
    from horovod_tpu import spmd
    from horovod_tpu.models import ResNet50

    hvd.init()

    devices = jax.devices()
    n_dev = len(devices)
    per_chip_batch = int(os.environ.get("HVD_BENCH_BATCH", "256"))
    batch = per_chip_batch * n_dev
    image_size = 224
    # Timed in chunks with a value fetch per chunk: on the experimental
    # axon platform block_until_ready() can return before execution
    # finishes, and very deep async queues measure erratically — a
    # float() fetch is the only reliable sync point.
    # Median-of-chunks timing: the host VM sees bursty external
    # interference (see benchmarks/collective_bench.py), so a single
    # long mean can absorb a bad window; per-chunk medians are robust.
    warmup_steps, chunk_steps, chunks = 5, 25, 5

    mesh = spmd.create_mesh({"data": n_dev}, devices=devices)
    model = ResNet50(num_classes=1000, dtype=jnp.bfloat16,
                     axis_name="data")
    rng = jax.random.key(0)
    images = jax.random.normal(
        rng, (batch, image_size, image_size, 3), jnp.bfloat16)
    labels = jnp.zeros((batch,), jnp.int32)
    if n_dev > 1:
        images = jax.device_put(images, spmd.batch_sharding(mesh))
        labels = jax.device_put(labels, spmd.batch_sharding(mesh))

    variables = jax.jit(lambda r, x: model.init(r, x, train=True))(
        rng, images)
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})
    # The framework's gradient path: optax sgd wrapped so update()
    # first pmeans grads over the mesh data axis (in-jit
    # DistributedOptimizer — the reference's compute_gradients
    # override, done where XLA can fuse it).
    tx = hvd.DistributedOptimizer(
        optax.sgd(0.01, momentum=0.9), axis="data")
    opt_state = tx.init(params)
    # Framework parameter broadcast: a no-op world of 1 still routes
    # through negotiation, matching user startup.
    params = hvd.broadcast_parameters(params, root_rank=0)

    def loss_fn(p, bs, x, y):
        logits, updates = model.apply(
            {"params": p, "batch_stats": bs}, x, train=True,
            mutable=["batch_stats"])
        one_hot = jax.nn.one_hot(y, 1000)
        loss = -jnp.mean(jnp.sum(
            jax.nn.log_softmax(logits) * one_hot, axis=-1))
        return loss, updates["batch_stats"]

    def step_body(p, bs, os_, x, y):
        (loss, new_bs), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(p, bs, x, y)
        updates, new_os = tx.update(grads, os_, p)
        new_p = optax.apply_updates(p, updates)
        return new_p, new_bs, new_os, loss

    # Always shard_map (a size-1 mesh included) so the mesh axis is in
    # scope for the DistributedOptimizer's gradient pmean and the
    # cross-replica batchnorm — the same program a multi-chip run jits.
    from jax.sharding import PartitionSpec as P
    rep = P()
    step_body = jaxshim.shard_map(
        step_body, mesh=mesh,
        in_specs=(rep, rep, rep, P("data"), P("data")),
        out_specs=(rep, rep, rep, rep))

    # Donated buffers: params/batch_stats/opt_state update in place —
    # no spare HBM copy of the weights per step. Compile ONCE via the
    # AOT path and drive every call through the compiled executable
    # (a plain jit call would compile a second copy).
    train_step = jax.jit(step_body, donate_argnums=(0, 1, 2)).lower(
        params, batch_stats, opt_state, images, labels).compile()

    # MFU uses analytic MODEL flops: ResNet-50 @224 is 4.089 G MACs
    # per forward image (the widely-quoted "4.09 GFLOPs" is the MACs
    # convention); MFU counts 2 flops per MAC (the PaLM / scaling-book
    # convention, same basis as the chip's peak spec) and 3x forward
    # for the train step. Cross-check: XLA's own cost analysis reports
    # 7.97 GF/img for the compiled forward — 0.97x this model count,
    # i.e. the step executes essentially zero non-model flops (no
    # remat/layout waste); ``flops_ratio`` below reports it per run.
    model_step_flops = 3 * (2 * 4.089e9) * batch
    cost_error = None
    hw_step_bytes = None
    try:
        ca = train_step.cost_analysis()
        ca = ca[0] if isinstance(ca, (list, tuple)) else ca
        hw_step_flops = float(ca["flops"])
        if not np.isfinite(hw_step_flops) or hw_step_flops <= 0:
            raise ValueError(f"bad flops: {hw_step_flops}")
        ba = float(ca.get("bytes accessed", 0) or 0)
        hw_step_bytes = ba if np.isfinite(ba) and ba > 0 else None
    except Exception as e:
        # Surface the regression instead of silently thinning the
        # report: hfu/flops_ratio/roofline fields will be absent and
        # this says why.
        hw_step_flops = None
        cost_error = repr(e)

    from horovod_tpu.utils.timing import steady_state_sec_per_step

    st = {"p": params, "bs": batch_stats, "os": opt_state}

    def one_step():
        st["p"], st["bs"], st["os"], loss = train_step(
            st["p"], st["bs"], st["os"], images, labels)
        return loss

    sec_per_step = steady_state_sec_per_step(
        one_step, lambda l: float(l), warmup_steps=warmup_steps,
        chunks=chunks, chunk_steps=chunk_steps)

    img_per_sec = batch / sec_per_step
    per_chip = img_per_sec / n_dev
    peak = _peak_flops(n_dev)
    mfu = (model_step_flops / sec_per_step) / peak
    result = {
        "metric": "resnet50_hvd_train_images_per_sec_per_chip",
        "value": round(per_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(per_chip / BASELINE_IMG_PER_SEC_PER_DEVICE, 3),
        "mfu": round(mfu, 4),
        "framework_in_loop": True,
        "n_devices": n_dev,
    }
    if hw_step_flops is not None:
        result["hfu"] = round((hw_step_flops / sec_per_step) / peak, 4)
        result["flops_ratio_executed_vs_model"] = round(
            hw_step_flops / model_step_flops, 3)
    if cost_error is not None:
        result["cost_analysis_unavailable"] = cost_error
    if hw_step_bytes is not None:
        # Roofline readout: this workload is HBM-bound on every TPU
        # generation in _PEAK_HBM (arithmetic intensity far below the
        # flops/bandwidth crossover), so the honest optimization
        # metric is achieved bandwidth and MFU relative to the
        # PROGRAM's roofline cap — see docs/benchmarks.md "MFU
        # roofline study" for the ablation behind this.
        # hw_step_bytes is set only after hw_step_flops validated, so
        # flops is always real here.
        hbm_peak = _PEAK_HBM.get(_tpu_gen(), _PEAK_HBM["v5e"]) * n_dev
        cap = min(hw_step_flops / hw_step_bytes * hbm_peak / peak, 1.0)
        result["bytes_accessed_GB"] = round(hw_step_bytes / 1e9, 2)
        result["achieved_hbm_GBps"] = round(
            hw_step_bytes / sec_per_step / 1e9, 1)
        result["hbm_bw_utilization"] = round(
            hw_step_bytes / sec_per_step / hbm_peak, 4)
        result["roofline_mfu_cap"] = round(
            cap * model_step_flops / hw_step_flops, 4)
        result["mfu_vs_roofline"] = round(
            result["mfu"] / result["roofline_mfu_cap"], 4)

    # Second, compute-bound metric: transformer-LM training MFU (the
    # proof the ResNet number is the workload's roofline, not the
    # framework). Failure must not cost the primary metric.
    try:
        lm = _bench_transformer(n_dev)
        result["transformer_hvd_train_mfu"] = lm["mfu"]
        result["transformer"] = lm
    except Exception as e:
        result["transformer_error"] = repr(e)
    print(json.dumps(result))
    hvd.shutdown()


if __name__ == "__main__":
    main()
