"""Synthetic ResNet-50 throughput benchmark (driver-run, real TPU).

TPU-native re-founding of the reference's synthetic benchmarks
(reference: examples/pytorch_synthetic_benchmark.py:95-110,
examples/tensorflow_synthetic_benchmark.py; docs/benchmarks.md:12-33):
same workload (ResNet-50, synthetic ImageNet-shaped data, SGD-momentum),
measured as images/sec on this host's chip(s).

Baseline: the reference's published example readout is 1656.82 img/s on
16 Pascal GPUs = 103.55 img/s per device (docs/benchmarks.md:29-33).
``vs_baseline`` is img/s-per-chip divided by that number.

Prints exactly one JSON line:
    {"metric": ..., "value": N, "unit": "images/sec/chip", "vs_baseline": N}
"""

from __future__ import annotations

import json
import sys
import time

BASELINE_IMG_PER_SEC_PER_DEVICE = 103.55


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from horovod_tpu.models import ResNet50

    devices = jax.devices()
    n_dev = len(devices)
    per_chip_batch = 128
    batch = per_chip_batch * n_dev
    image_size = 224
    # Timed in chunks with a value fetch per chunk: on the experimental
    # axon platform block_until_ready() can return before execution
    # finishes, and very deep async queues measure erratically — a
    # float() fetch is the only reliable sync point.
    warmup_steps, chunk_steps, chunks = 5, 10, 3

    model = ResNet50(num_classes=1000, dtype=jnp.bfloat16,
                     axis_name=None)
    rng = jax.random.key(0)
    images = jax.random.normal(
        rng, (batch, image_size, image_size, 3), jnp.bfloat16)
    labels = jnp.zeros((batch,), jnp.int32)

    if n_dev > 1:
        from horovod_tpu import spmd
        mesh = spmd.create_mesh({"data": n_dev}, devices=devices)
        images = jax.device_put(images, spmd.batch_sharding(mesh))
        labels = jax.device_put(labels, spmd.batch_sharding(mesh))

    variables = jax.jit(lambda r, x: model.init(r, x, train=True))(
        rng, images)
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})
    tx = optax.sgd(0.01, momentum=0.9)
    opt_state = tx.init(params)

    def loss_fn(p, bs, x, y):
        logits, updates = model.apply(
            {"params": p, "batch_stats": bs}, x, train=True,
            mutable=["batch_stats"])
        one_hot = jax.nn.one_hot(y, 1000)
        loss = -jnp.mean(jnp.sum(
            jax.nn.log_softmax(logits) * one_hot, axis=-1))
        return loss, updates["batch_stats"]

    @jax.jit
    def train_step(p, bs, os_, x, y):
        (loss, new_bs), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(p, bs, x, y)
        updates, new_os = tx.update(grads, os_, p)
        new_p = optax.apply_updates(p, updates)
        return new_p, new_bs, new_os, loss

    for _ in range(warmup_steps):
        params, batch_stats, opt_state, loss = train_step(
            params, batch_stats, opt_state, images, labels)
    float(loss)  # real sync (see note above)

    t0 = time.perf_counter()
    for _ in range(chunks):
        for _ in range(chunk_steps):
            params, batch_stats, opt_state, loss = train_step(
                params, batch_stats, opt_state, images, labels)
        float(loss)
    dt = time.perf_counter() - t0

    img_per_sec = batch * chunk_steps * chunks / dt
    per_chip = img_per_sec / n_dev
    print(json.dumps({
        "metric": "resnet50_synthetic_images_per_sec_per_chip",
        "value": round(per_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(per_chip / BASELINE_IMG_PER_SEC_PER_DEVICE, 3),
    }))


if __name__ == "__main__":
    main()
