"""Long-context Transformer training: dp x tp x sp on one mesh —
capability the reference does not have (SURVEY §5: no sequence
parallelism anywhere).

Single process, all visible devices:
    python examples/transformer_long_context.py --seq-len 8192
"""

import argparse

import numpy as np
import jax
import optax

from horovod_tpu import spmd
from horovod_tpu.models.transformer import TransformerConfig, TransformerLM
from horovod_tpu.parallel import (
    Trainer, TrainerConfig, make_chunked_lm_loss, make_ring_attention,
)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--seq-len", type=int, default=4096)
    p.add_argument("--batch-size", type=int, default=4)
    p.add_argument("--layers", type=int, default=4)
    p.add_argument("--heads", type=int, default=8)
    p.add_argument("--head-dim", type=int, default=64)
    p.add_argument("--steps", type=int, default=10,
                   help="approximate timed steps (rounded up to whole "
                        "chunks; ~10 extra warmup steps always run)")
    p.add_argument("--data", type=int, default=None, help="dp axis size")
    p.add_argument("--seq", type=int, default=None, help="sp axis size")
    p.add_argument("--model-par", type=int, default=None,
                   help="tp axis size")
    p.add_argument("--sp-mode", choices=["ring", "ulysses"],
                   default="ring",
                   help="sequence-parallel flavor: kv ring rotation or "
                        "all-to-all head exchange")
    args = p.parse_args()

    n = len(jax.devices())
    # default: all devices on the sequence axis (pure long-context)
    dp = args.data or 1
    tp = args.model_par or 1
    sp = args.seq or (n // (dp * tp))
    mesh = spmd.create_mesh({"data": dp, "seq": sp, "model": tp})
    print(f"mesh: data={dp} seq={sp} model={tp}")

    if args.sp_mode == "ulysses":
        from horovod_tpu.parallel import make_ulysses_attention
        if tp > 1:
            p.error("--sp-mode ulysses is incompatible with "
                    "--model-par > 1 (the head dim is ulysses' "
                    "exchange currency); use --sp-mode ring with tp")
        attn = make_ulysses_attention(mesh, data_axis="data",
                                      seq_axis="seq")
    else:
        attn = make_ring_attention(
            mesh, data_axis="data", seq_axis="seq",
            model_axis="model" if tp > 1 else None)
    cfg = TransformerConfig(
        vocab_size=32000, num_layers=args.layers, num_heads=args.heads,
        head_dim=args.head_dim, max_seq_len=args.seq_len,
        attention_fn=attn)
    trainer = Trainer(
        TransformerLM(cfg), mesh, optax.adamw(3e-4),
        TrainerConfig(data_axis="data",
                      model_axis="model" if tp > 1 else None,
                      seq_axis="seq"),
        # Chunked vocab loss: at vocab 32k x long context, full fp32
        # logits would dominate HBM (3.9 GB at batch 8 x seq 4096).
        loss_fn=make_chunked_lm_loss(chunk=1024))

    rng = np.random.RandomState(0)
    tokens = rng.randint(0, 32000,
                         (args.batch_size, args.seq_len)).astype(np.int32)
    # Place the (synthetic, fixed) batch on the mesh ONCE. A fresh
    # numpy batch per step would be re-uploaded every call — correct,
    # but the host->device transfer latency then hides the training
    # speed this benchmark measures (on remotely-attached TPUs it can
    # dominate 10:1). Real input pipelines double-buffer for the same
    # reason.
    batch = {"tokens": jax.device_put(tokens, trainer.batch_sharding)}
    state = trainer.init(jax.random.key(0), batch)

    from horovod_tpu.utils.timing import steady_state_sec_per_step

    last = {}

    def one_step():
        last["state"], last["loss"] = trainer.train_step(
            last.get("state", state), batch)
        return last["loss"]

    chunks = 4
    sec = steady_state_sec_per_step(
        one_step, lambda l: float(l),
        warmup_steps=10, chunks=chunks,
        chunk_steps=-(-args.steps // chunks))  # ceil: >= --steps timed
    loss = float(last["loss"])
    tok_s = args.batch_size * args.seq_len / sec
    print(f"loss {loss:.4f}; {tok_s:,.0f} tokens/sec "
          f"@ seq_len {args.seq_len}")


if __name__ == "__main__":
    main()
