"""Synthetic ResNet benchmark — img/sec ± CI, per device and total
(reference: examples/pytorch_synthetic_benchmark.py:1-110,
examples/tensorflow_synthetic_benchmark.py).

Single-process SPMD over all visible devices (the TPU-native shape):
    python examples/jax_synthetic_benchmark.py --batch-size 128
Multi-process via the launcher also works; each process then benches
its own chip and the allreduce rides the negotiated runtime.
"""

import argparse
import time

import numpy as np
import jax
import jax.numpy as jnp
import optax

import horovod_tpu.jax as hvd
from horovod_tpu import spmd
from horovod_tpu.models import ResNet50, ResNet101


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="resnet50",
                   choices=["resnet50", "resnet101"])
    p.add_argument("--batch-size", type=int, default=128,
                   help="per-device batch size")
    p.add_argument("--num-warmup-batches", type=int, default=5)
    p.add_argument("--num-batches-per-iter", type=int, default=10)
    p.add_argument("--num-iters", type=int, default=3)
    p.add_argument("--fp16-allreduce", action="store_true",
                   help="(kept for CLI parity; SPMD grads are averaged "
                        "in-graph where XLA picks the wire type)")
    args = p.parse_args()

    hvd.init()
    devices = jax.devices()
    n_dev = len(devices)
    mesh = spmd.create_mesh({"data": n_dev})

    model_cls = ResNet50 if args.model == "resnet50" else ResNet101
    model = model_cls(num_classes=1000, dtype=jnp.bfloat16)
    batch = args.batch_size * n_dev

    rng = jax.random.key(0)
    images = jax.device_put(
        jax.random.normal(rng, (batch, 224, 224, 3), jnp.bfloat16),
        spmd.batch_sharding(mesh))
    labels = jax.device_put(jnp.zeros((batch,), jnp.int32),
                            spmd.batch_sharding(mesh))

    variables = jax.jit(lambda r, x: model.init(r, x, train=True))(
        rng, images)
    params, batch_stats = variables["params"], variables["batch_stats"]
    tx = optax.sgd(0.01, momentum=0.9)
    opt_state = tx.init(params)

    def loss_fn(p, bs, x, y):
        logits, upd = model.apply({"params": p, "batch_stats": bs}, x,
                                  train=True, mutable=["batch_stats"])
        oh = jax.nn.one_hot(y, 1000)
        return -jnp.mean(jnp.sum(jax.nn.log_softmax(logits) * oh, -1)), \
            upd["batch_stats"]

    @jax.jit
    def step(p, bs, os_, x, y):
        (l, nbs), g = jax.value_and_grad(loss_fn, has_aux=True)(
            p, bs, x, y)
        u, nos = tx.update(g, os_, p)
        return optax.apply_updates(p, u), nbs, nos, l

    def run_batches(n):
        nonlocal params, batch_stats, opt_state
        for _ in range(n):
            params, batch_stats, opt_state, loss = step(
                params, batch_stats, opt_state, images, labels)
        float(loss)  # hard sync (block_until_ready is unreliable here)

    run_batches(args.num_warmup_batches)
    img_secs = []
    for i in range(args.num_iters):
        t0 = time.perf_counter()
        run_batches(args.num_batches_per_iter)
        dt = time.perf_counter() - t0
        ips = batch * args.num_batches_per_iter / dt
        if hvd.rank() == 0:
            print(f"Iter #{i}: {ips:.1f} img/sec ({n_dev} device(s))")
        img_secs.append(ips)

    if hvd.rank() == 0:
        mean, conf = np.mean(img_secs), 1.96 * np.std(img_secs)
        print(f"Img/sec per device: {mean / n_dev:.1f} "
              f"+-{conf / n_dev:.1f}")
        print(f"Total img/sec on {n_dev} device(s): "
              f"{mean * hvd.size():.1f} +-{conf * hvd.size():.1f}")
    hvd.shutdown()


if __name__ == "__main__":
    main()
