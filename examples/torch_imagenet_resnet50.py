"""ImageNet-scale ResNet-50 training with the torch adapter
(reference: examples/pytorch_imagenet_resnet50.py — fp16 allreduce
compression, batches-per-allreduce gradient accumulation, linear LR
warmup per arXiv:1706.02677, rank-0 checkpointing with broadcast
resume).

Data is synthetic ImageNet-shaped by default (this benchmark harness
is what BASELINE.json's configs sweep); point --train-dir at an
ImageFolder-style tree to train on real data if torchvision is
available.

Run:  python -m horovod_tpu.run -np 8 python \
          examples/torch_imagenet_resnet50.py --fp16-allreduce
"""

import argparse
import math
import os

import numpy as np
import torch
import torch.nn as nn
import torch.nn.functional as F

import horovod_tpu.torch as hvd


class Bottleneck(nn.Module):
    """Standard ResNet v1.5 bottleneck (1x1 -> 3x3(stride) -> 1x1)."""

    expansion = 4

    def __init__(self, cin, planes, stride=1):
        super().__init__()
        cout = planes * self.expansion
        self.conv1 = nn.Conv2d(cin, planes, 1, bias=False)
        self.bn1 = nn.BatchNorm2d(planes)
        self.conv2 = nn.Conv2d(planes, planes, 3, stride=stride,
                               padding=1, bias=False)
        self.bn2 = nn.BatchNorm2d(planes)
        self.conv3 = nn.Conv2d(planes, cout, 1, bias=False)
        self.bn3 = nn.BatchNorm2d(cout)
        self.down = None
        if stride != 1 or cin != cout:
            self.down = nn.Sequential(
                nn.Conv2d(cin, cout, 1, stride=stride, bias=False),
                nn.BatchNorm2d(cout))

    def forward(self, x):
        y = F.relu(self.bn1(self.conv1(x)))
        y = F.relu(self.bn2(self.conv2(y)))
        y = self.bn3(self.conv3(y))
        s = x if self.down is None else self.down(x)
        return F.relu(y + s)


class ResNet50(nn.Module):
    """ResNet-50: [3, 4, 6, 3] bottleneck stages (the reference uses
    torchvision.models.resnet50; this is the same architecture,
    self-contained)."""

    def __init__(self, num_classes=1000, width=64):
        super().__init__()
        self.stem = nn.Sequential(
            nn.Conv2d(3, width, 7, stride=2, padding=3, bias=False),
            nn.BatchNorm2d(width), nn.ReLU(inplace=True),
            nn.MaxPool2d(3, stride=2, padding=1))
        layers = []
        cin = width
        for planes, blocks, stride in ((width, 3, 1), (width * 2, 4, 2),
                                       (width * 4, 6, 2),
                                       (width * 8, 3, 2)):
            for b in range(blocks):
                layers.append(Bottleneck(cin, planes,
                                         stride if b == 0 else 1))
                cin = planes * Bottleneck.expansion
        self.body = nn.Sequential(*layers)
        self.head = nn.Linear(cin, num_classes)

    def forward(self, x):
        x = self.body(self.stem(x))
        x = F.adaptive_avg_pool2d(x, 1).flatten(1)
        return self.head(x)


def synthetic_batches(rank, n, batch, image_size, num_classes):
    rng = np.random.RandomState(1000 + rank)
    for _ in range(n):
        x = torch.from_numpy(
            rng.rand(batch, 3, image_size, image_size).astype(np.float32))
        y = torch.from_numpy(rng.randint(0, num_classes, batch))
        yield x, y


def main():
    p = argparse.ArgumentParser(
        description="ResNet-50 ImageNet training (horovod_tpu torch)")
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--batches-per-allreduce", type=int, default=1,
                   help="local gradient-accumulation sub-batches per "
                        "allreduce; multiplies the effective batch")
    p.add_argument("--epochs", type=int, default=90)
    p.add_argument("--steps-per-epoch", type=int, default=16,
                   help="synthetic batches per epoch")
    p.add_argument("--base-lr", type=float, default=0.0125)
    p.add_argument("--warmup-epochs", type=float, default=5)
    p.add_argument("--momentum", type=float, default=0.9)
    p.add_argument("--wd", type=float, default=5e-5)
    p.add_argument("--fp16-allreduce", action="store_true",
                   help="fp16 compression on the gradient wire")
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--num-classes", type=int, default=1000)
    p.add_argument("--width", type=int, default=64,
                   help="stem width (64 = real ResNet-50; smaller for "
                        "smoke tests)")
    p.add_argument("--checkpoint-format",
                   default="./checkpoint-{epoch}.pth.tar")
    p.add_argument("--seed", type=int, default=42)
    args = p.parse_args()

    hvd.init()
    torch.manual_seed(args.seed)
    verbose = hvd.rank() == 0

    # Resume from the newest checkpoint rank 0 can see; the epoch is
    # broadcast so every rank agrees (reference behavior).
    resume_from_epoch = 0
    for try_epoch in range(args.epochs, 0, -1):
        if os.path.exists(args.checkpoint_format.format(epoch=try_epoch)):
            resume_from_epoch = try_epoch
            break
    resume_from_epoch = int(hvd.broadcast(
        torch.tensor(resume_from_epoch), root_rank=0,
        name="resume_from_epoch").item())

    model = ResNet50(args.num_classes, width=args.width)
    # LR scaled by world size AND accumulation factor
    # (arXiv:1706.02677 linear scaling rule).
    optimizer = torch.optim.SGD(
        model.parameters(),
        lr=args.base_lr * args.batches_per_allreduce * hvd.size(),
        momentum=args.momentum, weight_decay=args.wd)

    if resume_from_epoch > 0 and hvd.rank() == 0:
        ckpt = torch.load(
            args.checkpoint_format.format(epoch=resume_from_epoch),
            weights_only=True)
        model.load_state_dict(ckpt["model"])
        optimizer.load_state_dict(ckpt["optimizer"])

    # Rank 0's (possibly restored) weights and optimizer state become
    # everyone's; broadcast_optimizer_state materializes worker state
    # when only rank 0 restored (the asymmetric shape).
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    hvd.broadcast_optimizer_state(optimizer, root_rank=0)

    compression = (hvd.Compression.fp16 if args.fp16_allreduce
                   else hvd.Compression.none)
    optimizer = hvd.DistributedOptimizer(
        optimizer, named_parameters=model.named_parameters(),
        compression=compression,
        backward_passes_per_step=args.batches_per_allreduce)

    def adjust_lr(epoch, batch_idx):
        """Warmup 1/N -> 1 over warmup_epochs, then /10 at 30/60/80."""
        if epoch < args.warmup_epochs:
            ep = epoch + float(batch_idx + 1) / args.steps_per_epoch
            adj = (1.0 / hvd.size()
                   * (ep * (hvd.size() - 1) / args.warmup_epochs + 1))
        elif epoch < 30:
            adj = 1.0
        elif epoch < 60:
            adj = 1e-1
        elif epoch < 80:
            adj = 1e-2
        else:
            adj = 1e-3
        lr = (args.base_lr * hvd.size() * args.batches_per_allreduce
              * adj)
        for group in optimizer.param_groups:
            group["lr"] = lr

    model.train()
    sub = args.batch_size
    for epoch in range(resume_from_epoch, args.epochs):
        batches = synthetic_batches(
            hvd.rank(), args.steps_per_epoch,
            sub * args.batches_per_allreduce, args.image_size,
            args.num_classes)
        loss_sum, loss_count = 0.0, 0
        for batch_idx, (data, target) in enumerate(batches):
            adjust_lr(epoch, batch_idx)
            optimizer.zero_grad()
            n_sub = math.ceil(len(data) / sub)
            for i in range(0, len(data), sub):
                loss = F.cross_entropy(model(data[i:i + sub]),
                                       target[i:i + sub])
                loss_sum += loss.item()
                loss_count += 1
                # average gradients over the local sub-batches
                (loss / n_sub).backward()
            optimizer.step()
        # Epoch metric averaged over sub-batches AND ranks, like the
        # reference's Metric helper (allreduce of the running average).
        avg_loss = hvd.allreduce(
            torch.tensor(loss_sum / max(loss_count, 1)),
            name="train_loss").item()
        if verbose:
            print(f"epoch {epoch + 1}/{args.epochs}: "
                  f"loss {avg_loss:.4f} "
                  f"lr {optimizer.param_groups[0]['lr']:.5f}")
        if hvd.rank() == 0:
            torch.save(
                {"model": model.state_dict(),
                 "optimizer": optimizer.state_dict()},
                args.checkpoint_format.format(epoch=epoch + 1))
    hvd.shutdown()


if __name__ == "__main__":
    main()
