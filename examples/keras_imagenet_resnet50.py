"""ImageNet-scale ResNet-50 training with the Keras adapter
(reference: examples/keras_imagenet_resnet50.py — LR warmup + staged
decay callbacks, metric averaging, fp16 allreduce compression, rank-0
checkpointing) plus a --fusion-threshold flag so the
HOROVOD_FUSION_THRESHOLD sweep named in BASELINE.json runs from one
command.

Data is synthetic ImageNet-shaped; the model is
keras.applications.ResNet50 (architecture identical to the
reference's keras ResNet-50).

Run:  python -m horovod_tpu.run -np 8 python \
          examples/keras_imagenet_resnet50.py --fp16-allreduce
"""

import argparse
import os


def main():
    p = argparse.ArgumentParser(
        description="ResNet-50 ImageNet training (horovod_tpu keras)")
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--epochs", type=int, default=90)
    p.add_argument("--steps-per-epoch", type=int, default=16)
    p.add_argument("--base-lr", type=float, default=0.0125)
    p.add_argument("--warmup-epochs", type=int, default=5)
    p.add_argument("--momentum", type=float, default=0.9)
    p.add_argument("--wd", type=float, default=5e-5)
    p.add_argument("--fp16-allreduce", action="store_true")
    p.add_argument("--fusion-threshold", type=int, default=None,
                   help="HOROVOD_FUSION_THRESHOLD bytes for this run "
                        "(the BASELINE.json sweep knob); must be set "
                        "before hvd.init reads the env")
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--num-classes", type=int, default=1000)
    p.add_argument("--checkpoint-dir", default="./checkpoints")
    args = p.parse_args()

    if args.fusion_threshold is not None:
        os.environ["HOROVOD_FUSION_THRESHOLD"] = \
            str(args.fusion_threshold)

    import numpy as np
    import keras
    import horovod_tpu.keras as hvd

    hvd.init()
    keras.utils.set_random_seed(42)
    verbose = 1 if hvd.rank() == 0 else 0

    model = keras.applications.ResNet50(
        weights=None, classes=args.num_classes,
        input_shape=(args.image_size, args.image_size, 3))

    # LR pre-scaled by world size; the warmup callback ramps 1 -> size
    # from the UNSCALED base (arXiv:1706.02677), so compile with the
    # base LR and let the callbacks own the schedule.
    opt = keras.optimizers.SGD(learning_rate=args.base_lr,
                               momentum=args.momentum,
                               weight_decay=args.wd)
    compression = (hvd.Compression.fp16 if args.fp16_allreduce
                   else hvd.Compression.none)
    model.compile(
        loss="sparse_categorical_crossentropy",
        optimizer=hvd.DistributedOptimizer(opt,
                                           compression=compression),
        metrics=["accuracy"])

    callbacks = [
        # rank 0's initial weights become everyone's
        hvd.callbacks.BroadcastGlobalVariablesCallback(0),
        # epoch metrics averaged over ranks, not just rank 0's shard
        hvd.callbacks.MetricAverageCallback(),
        # 1 -> size over the warmup epochs, then the /10 staircase at
        # 30/60/80 like the reference example
        hvd.callbacks.LearningRateWarmupCallback(
            warmup_epochs=args.warmup_epochs, verbose=verbose),
        # Explicit initial_lr: without it the callback would autodetect
        # from the optimizer AFTER warmup already scaled it by size,
        # double-applying the size factor (base*size^2).
        hvd.callbacks.LearningRateScheduleCallback(
            multiplier=hvd.size() * 1.0, initial_lr=args.base_lr,
            start_epoch=args.warmup_epochs, end_epoch=30),
        hvd.callbacks.LearningRateScheduleCallback(
            multiplier=hvd.size() * 1e-1, initial_lr=args.base_lr,
            start_epoch=30, end_epoch=60),
        hvd.callbacks.LearningRateScheduleCallback(
            multiplier=hvd.size() * 1e-2, initial_lr=args.base_lr,
            start_epoch=60, end_epoch=80),
        hvd.callbacks.LearningRateScheduleCallback(
            multiplier=hvd.size() * 1e-3, initial_lr=args.base_lr,
            start_epoch=80),
    ]
    if hvd.rank() == 0:
        os.makedirs(args.checkpoint_dir, exist_ok=True)
        callbacks.append(keras.callbacks.ModelCheckpoint(
            os.path.join(args.checkpoint_dir,
                         "checkpoint-{epoch}.weights.h5"),
            save_weights_only=True))

    rng = np.random.RandomState(1000 + hvd.rank())
    n = args.batch_size * args.steps_per_epoch
    x = rng.rand(n, args.image_size, args.image_size, 3).astype(
        np.float32)
    y = rng.randint(0, args.num_classes, n)

    model.fit(x, y, batch_size=args.batch_size, epochs=args.epochs,
              callbacks=callbacks, verbose=verbose)
    hvd.shutdown()


if __name__ == "__main__":
    main()
