"""MNIST with the torch adapter (reference: examples/pytorch_mnist.py).

Run:  python -m horovod_tpu.run -np 2 python examples/torch_mnist.py
"""

import argparse

import numpy as np
import torch
import torch.nn as nn
import torch.nn.functional as F

import horovod_tpu.torch as hvd


class Net(nn.Module):
    """(reference: examples/pytorch_mnist.py:42-60)"""

    def __init__(self):
        super().__init__()
        self.conv1 = nn.Conv2d(1, 10, kernel_size=5)
        self.conv2 = nn.Conv2d(10, 20, kernel_size=5)
        self.fc1 = nn.Linear(320, 50)
        self.fc2 = nn.Linear(50, 10)

    def forward(self, x):
        x = F.relu(F.max_pool2d(self.conv1(x), 2))
        x = F.relu(F.max_pool2d(self.conv2(x), 2))
        x = x.view(-1, 320)
        x = F.relu(self.fc1(x))
        return F.log_softmax(self.fc2(x), dim=1)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--epochs", type=int, default=2)
    parser.add_argument("--batch-size", type=int, default=64)
    parser.add_argument("--lr", type=float, default=0.01)
    parser.add_argument("--fp16-allreduce", action="store_true")
    args = parser.parse_args()

    hvd.init()
    torch.manual_seed(42)

    model = Net()
    # lr scaled by world size (reference: pytorch_mnist.py:*lr scaling)
    optimizer = torch.optim.SGD(model.parameters(),
                                lr=args.lr * hvd.size(), momentum=0.5)

    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    hvd.broadcast_optimizer_state(optimizer, root_rank=0)

    compression = (hvd.Compression.fp16 if args.fp16_allreduce
                   else hvd.Compression.none)
    optimizer = hvd.DistributedOptimizer(
        optimizer, named_parameters=model.named_parameters(),
        compression=compression)

    rng = np.random.RandomState(100 + hvd.rank())
    x = torch.from_numpy(rng.rand(512, 1, 28, 28).astype(np.float32))
    y = torch.from_numpy(rng.randint(0, 10, 512))

    model.train()
    steps = len(x) // args.batch_size
    for epoch in range(args.epochs):
        for i in range(steps):
            sl = slice(i * args.batch_size, (i + 1) * args.batch_size)
            optimizer.zero_grad()
            loss = F.nll_loss(model(x[sl]), y[sl])
            loss.backward()
            optimizer.step()
        if hvd.rank() == 0:
            print(f"epoch {epoch}: loss {loss.item():.4f}")
    hvd.shutdown()


if __name__ == "__main__":
    main()
