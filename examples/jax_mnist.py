"""MNIST end-to-end with the jax adapter + background runtime
(reference: examples/pytorch_mnist.py, examples/tensorflow_mnist.py).

Run:  python -m horovod_tpu.run -np 2 python examples/jax_mnist.py

Synthetic MNIST-shaped data is used so the example runs hermetically;
swap in real data trivially.
"""

import argparse

import numpy as np
import jax
import jax.numpy as jnp
import optax

import horovod_tpu.jax as hvd
from horovod_tpu.models import MnistConvNet


def synthetic_mnist(rank: int, n: int = 512):
    rng = np.random.RandomState(1234 + rank)  # rank-sharded "dataset"
    x = rng.rand(n, 28, 28, 1).astype(np.float32)
    y = rng.randint(0, 10, n).astype(np.int32)
    return x, y


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--epochs", type=int, default=2)
    parser.add_argument("--batch-size", type=int, default=64)
    parser.add_argument("--lr", type=float, default=0.01)
    args = parser.parse_args()

    hvd.init()
    model = MnistConvNet()
    rng = jax.random.key(1)
    params = model.init(rng, jnp.zeros((1, 28, 28, 1)))

    # Linear-scaling rule: lr * world size (reference:
    # examples/pytorch_mnist.py lr scaling).
    tx = optax.sgd(args.lr * hvd.size(), momentum=0.9)
    opt_state = tx.init(params)

    # One-time state broadcast so all ranks start identically
    # (reference: hvd.broadcast_parameters(model.state_dict())).
    params = hvd.broadcast_parameters(params, root_rank=0)
    opt_state = hvd.broadcast_optimizer_state(opt_state, root_rank=0)

    @jax.jit
    def grad_step(params, x, y):
        def loss_fn(p):
            logits = model.apply(p, x)
            oh = jax.nn.one_hot(y, 10)
            return -jnp.mean(jnp.sum(jax.nn.log_softmax(logits) * oh, -1))
        return jax.value_and_grad(loss_fn)(params)

    x, y = synthetic_mnist(hvd.rank())
    steps = len(x) // args.batch_size
    for epoch in range(args.epochs):
        for i in range(steps):
            sl = slice(i * args.batch_size, (i + 1) * args.batch_size)
            loss, grads = grad_step(params, x[sl], y[sl])
            # Gradient averaging through the negotiated runtime
            # (fusion, timeline, autotune all apply).
            grads = hvd.allreduce_gradients(
                jax.tree_util.tree_map(np.asarray, grads))
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
        if hvd.rank() == 0:
            print(f"epoch {epoch}: loss {float(loss):.4f}")
    hvd.shutdown()


if __name__ == "__main__":
    main()
