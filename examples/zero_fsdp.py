"""ZeRO-1 and FSDP/ZeRO-3 on one mesh (no reference analog — the
reference replicates optimizer state on every worker).

Two memory-sharding flavors, both runnable on CPU:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/zero_fsdp.py

1. ``spmd.zero_optimizer`` (ZeRO-1, shard_map): reduce-scatter grads,
   Adam moments live 1/n per rank, update shards all-gathered.
2. ``TrainerConfig(fsdp_axis=...)`` (ZeRO-3, GSPMD): parameters AND
   moments sharded; XLA all-gathers weights just-in-time per layer.
"""

import numpy as np
import jax
import jax.numpy as jnp
import optax
from jax.sharding import PartitionSpec as P

from horovod_tpu import spmd
from horovod_tpu.models.transformer import TransformerConfig, TransformerLM
from horovod_tpu.parallel import Trainer, TrainerConfig

from horovod_tpu.compat import jaxshim


def zero1_demo():
    n = len(jax.devices())
    mesh = spmd.create_mesh({"data": n})
    rng = np.random.RandomState(0)
    X = rng.randn(8 * n, 32).astype(np.float32)
    y = (X @ rng.randn(32).astype(np.float32))
    params = {"w": np.zeros(32, np.float32)}

    inner = optax.chain(spmd.sharded_clip_by_global_norm(1.0),
                        optax.adam(0.05))
    tx = spmd.zero_optimizer(inner)
    specs = spmd.zero_state_specs(inner, params, n)

    def step(p, s, xb, yb):
        loss, g = jax.value_and_grad(
            lambda p: jnp.mean((xb @ p["w"] - yb) ** 2))(p)
        loss = jax.lax.pmean(loss, "data")
        u, s = tx.update(g, s, p)
        return optax.apply_updates(p, u), s, loss

    step = jax.jit(jaxshim.shard_map(
        step, mesh=mesh, in_specs=(P(), specs, P("data"), P("data")),
        out_specs=(P(), specs, P())))
    state = jax.jit(jaxshim.shard_map(
        tx.init, mesh=mesh, in_specs=(P(),), out_specs=specs))(params)

    for i in range(30):
        params, state, loss = step(params, state, X, y)
    mu = state[1][0].mu["w"]
    print(f"ZeRO-1: loss {float(loss):.5f}; moment shard/device = "
          f"{mu.sharding.shard_shape(mu.shape)[0]} of {mu.shape[0]}")


def fsdp_demo():
    n = len(jax.devices())
    mesh = spmd.create_mesh({"data": n})
    cfg = TransformerConfig(vocab_size=256, num_layers=2, num_heads=4,
                            head_dim=16, max_seq_len=32,
                            dtype=jnp.float32)
    trainer = Trainer(TransformerLM(cfg), mesh, optax.adam(1e-2),
                      TrainerConfig(model_axis=None, fsdp_axis="data"))
    tokens = np.tile(np.arange(32, dtype=np.int32)[None], (2 * n, 1))
    batch = {"tokens": jax.device_put(tokens, trainer.batch_sharding)}
    state = trainer.init(jax.random.key(0), batch)

    emb = state["params"]["params"]["embed"]["embedding"]
    local = emb.sharding.shard_shape(emb.shape)
    for _ in range(5):
        state, loss = trainer.train_step(state, batch)
    print(f"FSDP: loss {float(loss):.4f}; embed {tuple(emb.shape)} -> "
          f"{tuple(local)} per device (params+moments sharded)")


if __name__ == "__main__":
    zero1_demo()
    fsdp_demo()
