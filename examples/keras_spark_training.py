"""End-to-end Spark ML training demo
(reference: examples/keras_spark_rossmann.py — the reference's
flagship Spark workflow: prepare a tabular dataset with Spark, train a
Keras model data-parallel across Spark tasks via horovod.spark.run,
then predict on the driver with the trained weights).

The workload here is a compact Rossmann-shaped tabular regression —
categorical features through embeddings + continuous features through
dense layers — on synthetic data, so the example runs anywhere in
seconds while exercising the identical workflow:

  1. driver materializes a feature table (rows of categorical ids +
     continuous values + target);
  2. ``horovod_tpu.spark.run(train_fn, num_proc=N)`` ships the
     training function to N Spark tasks; each task trains on its
     row shard with a DistributedOptimizer (gradient allreduce over
     the horovod_tpu world wired through the Spark driver rendezvous);
  3. rank 0's trained weights come back to the driver, which scores a
     held-out split locally.

Run (with pyspark installed):
    python examples/keras_spark_training.py --num-proc 2
Demo mode without pyspark (the in-repo process-backed stand-in,
same task/partition shape as Spark local mode):
    HVD_FAKE_PYSPARK=1 python examples/keras_spark_training.py
"""

import argparse
import os
import sys

import numpy as np

N_STORES, N_DOW = 12, 7  # categorical vocab sizes
N_CONT = 3               # continuous features


def make_table(n_rows: int, seed: int):
    """Synthetic Rossmann-shaped rows: sales driven by store quality,
    day-of-week seasonality, and noisy continuous signals."""
    rng = np.random.RandomState(seed)
    store = rng.randint(0, N_STORES, n_rows)
    dow = rng.randint(0, N_DOW, n_rows)
    cont = rng.rand(n_rows, N_CONT).astype(np.float32)
    sales = (10.0 + store * 0.5 + np.sin(dow / 7.0 * 2 * np.pi) * 2.0
             + cont @ np.asarray([3.0, -2.0, 1.0], np.float32)
             + rng.randn(n_rows).astype(np.float32) * 0.1)
    return store, dow, cont, sales.astype(np.float32)


def build_model():
    import keras
    store_in = keras.layers.Input((1,), dtype="int32", name="store")
    dow_in = keras.layers.Input((1,), dtype="int32", name="dow")
    cont_in = keras.layers.Input((N_CONT,), name="cont")
    store_e = keras.layers.Flatten()(
        keras.layers.Embedding(N_STORES, 4)(store_in))
    dow_e = keras.layers.Flatten()(
        keras.layers.Embedding(N_DOW, 3)(dow_in))
    h = keras.layers.Concatenate()([store_e, dow_e, cont_in])
    h = keras.layers.Dense(32, activation="relu")(h)
    h = keras.layers.Dense(16, activation="relu")(h)
    out = keras.layers.Dense(1, name="sales")(h)
    return keras.Model([store_in, dow_in, cont_in], out)


def train_fn(epochs: int, batch_size: int, base_lr: float):
    """Runs INSIDE each Spark task with the horovod_tpu world up."""
    import keras
    import horovod_tpu.keras as hvd

    keras.utils.set_random_seed(42)
    model = build_model()
    opt = keras.optimizers.Adam(base_lr * hvd.size())
    model.compile(loss="mse",
                  optimizer=hvd.DistributedOptimizer(opt))

    # each rank trains on its own shard, like Spark partitions
    store, dow, cont, sales = make_table(2048, seed=100 + hvd.rank())
    model.fit([store, dow, cont], sales, batch_size=batch_size,
              epochs=epochs,
              callbacks=[
                  hvd.callbacks.BroadcastGlobalVariablesCallback(0),
                  hvd.callbacks.MetricAverageCallback(),
              ],
              verbose=2 if hvd.rank() == 0 else 0)
    # ship rank 0's weights back to the driver (reference: Rossmann
    # serializes the trained model back through the driver service)
    return [w.tolist() for w in model.get_weights()] \
        if hvd.rank() == 0 else None


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--num-proc", type=int, default=2)
    p.add_argument("--epochs", type=int, default=4)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--base-lr", type=float, default=0.01)
    args = p.parse_args()

    if os.environ.get("HVD_FAKE_PYSPARK") == "1":
        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        from tests import fake_pyspark
        fake_pyspark.install()

    import horovod_tpu.spark

    results = horovod_tpu.spark.run(
        train_fn, args=(args.epochs, args.batch_size, args.base_lr),
        num_proc=args.num_proc)
    weights = [np.asarray(w, np.float32) for w in results[0]]

    # driver-side scoring on a held-out split with rank 0's weights
    model = build_model()
    model.set_weights(weights)
    store, dow, cont, sales = make_table(512, seed=999)
    pred = model.predict([store, dow, cont], verbose=0).reshape(-1)
    rmse = float(np.sqrt(np.mean((pred - sales) ** 2)))
    base = float(np.sqrt(np.mean((sales.mean() - sales) ** 2)))
    print(f"driver-side holdout RMSE {rmse:.3f} "
          f"(predict-the-mean baseline {base:.3f})")
    assert rmse < base, "model learned nothing"


if __name__ == "__main__":
    main()
