"""Advanced Keras MNIST: the full callback composition
(reference: examples/keras_mnist_advanced.py — BroadcastGlobalVariables
+ MetricAverage + LearningRateWarmup + an LR schedule + rank-0
checkpointing in ONE run, with per-rank data sharding and validation).

This is the example that exercises warmup ramping INTO a staged decay
schedule with momentum correction, plus metric averaging across
ranks — the composition the reference uses for its accuracy-preserving
large-batch recipe (arXiv:1706.02677).

Run:  python -m horovod_tpu.run -np 4 python \
          examples/keras_mnist_advanced.py
"""

import argparse
import os
import tempfile

import numpy as np
import keras

import horovod_tpu.keras as hvd


def build_model():
    """(reference: examples/keras_mnist_advanced.py model — conv/pool
    stack + dropout head)"""
    return keras.Sequential([
        keras.layers.Input((28, 28, 1)),
        keras.layers.Conv2D(32, (3, 3), activation="relu"),
        keras.layers.Conv2D(64, (3, 3), activation="relu"),
        keras.layers.MaxPooling2D((2, 2)),
        keras.layers.Dropout(0.25),
        keras.layers.Flatten(),
        keras.layers.Dense(128, activation="relu"),
        keras.layers.Dropout(0.5),
        keras.layers.Dense(10, activation="softmax"),
    ])


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=8)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--warmup-epochs", type=int, default=2)
    p.add_argument("--base-lr", type=float, default=0.05)
    p.add_argument("--checkpoint-dir", default=None)
    args = p.parse_args()

    hvd.init()
    keras.utils.set_random_seed(42)
    verbose = 2 if hvd.rank() == 0 else 0

    model = build_model()
    # Compile with the UNSCALED base lr: the warmup callback ramps it
    # 1 -> size, then the schedule callbacks decay from the scaled
    # value with momentum correction on each jump.
    opt = keras.optimizers.SGD(learning_rate=args.base_lr,
                               momentum=0.9)
    model.compile(loss="sparse_categorical_crossentropy",
                  optimizer=hvd.DistributedOptimizer(opt),
                  metrics=["accuracy"])

    half = args.epochs // 2
    callbacks = [
        hvd.callbacks.BroadcastGlobalVariablesCallback(0),
        hvd.callbacks.MetricAverageCallback(),
        hvd.callbacks.LearningRateWarmupCallback(
            warmup_epochs=args.warmup_epochs, verbose=verbose),
        # Explicit initial_lr: without it the callback would autodetect
        # from the optimizer AFTER warmup already scaled it by size,
        # double-applying the size factor (base*size^2).
        hvd.callbacks.LearningRateScheduleCallback(
            multiplier=hvd.size() * 1.0, initial_lr=args.base_lr,
            start_epoch=args.warmup_epochs, end_epoch=half),
        hvd.callbacks.LearningRateScheduleCallback(
            multiplier=hvd.size() * 1e-1, initial_lr=args.base_lr,
            start_epoch=half),
    ]
    ckpt_dir = args.checkpoint_dir
    if hvd.rank() == 0:
        ckpt_dir = ckpt_dir or tempfile.mkdtemp(prefix="hvd-keras-")
        os.makedirs(ckpt_dir, exist_ok=True)
        callbacks.append(keras.callbacks.ModelCheckpoint(
            os.path.join(ckpt_dir, "checkpoint-{epoch}.weights.h5"),
            save_weights_only=True))

    # Per-rank shard of a synthetic MNIST-shaped set (each rank draws
    # a DIFFERENT shard, which is why MetricAverageCallback matters:
    # rank 0's local metrics alone would be a biased readout).
    rng = np.random.RandomState(100 + hvd.rank())
    x = rng.rand(1024, 28, 28, 1).astype(np.float32)
    y = rng.randint(0, 10, 1024)
    val_rng = np.random.RandomState(999)  # same validation everywhere
    xv = val_rng.rand(256, 28, 28, 1).astype(np.float32)
    yv = val_rng.randint(0, 10, 256)

    hist = model.fit(x, y, batch_size=args.batch_size,
                     epochs=args.epochs, validation_data=(xv, yv),
                     callbacks=callbacks, verbose=verbose)
    if hvd.rank() == 0:
        lrs = hist.history.get("lr", [])
        print(f"lr trajectory: {[round(float(v), 4) for v in lrs]}")
        print(f"final val_loss {hist.history['val_loss'][-1]:.4f}")
    hvd.shutdown()


if __name__ == "__main__":
    main()
