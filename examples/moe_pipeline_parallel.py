"""Expert + pipeline parallelism on virtual devices (no reference
analog — the reference scales batch only).

Two independent demonstrations on an 8-device mesh:
  1. dp x ep x tp: a Switch/top-2 MoE transformer with experts sharded
     over their own mesh axis, trained a few steps;
  2. dp x pp: a stage-stacked block tower streamed GPipe-style.

Run (CPU; no TPU needed):
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/moe_pipeline_parallel.py
"""

import numpy as np

import jax
import jax.numpy as jnp
import optax

from horovod_tpu import spmd
from horovod_tpu.models.transformer import TransformerConfig, TransformerLM
from horovod_tpu.parallel import (
    Trainer, TrainerConfig, make_pipeline_apply,
)


def moe_training():
    mesh = spmd.create_mesh({"data": 2, "expert": 2, "model": 2})
    cfg = TransformerConfig(
        vocab_size=256, num_layers=4, num_heads=4, head_dim=16,
        dtype=jnp.float32,
        num_experts=2, moe_every=2, moe_top_k=2)
    trainer = Trainer(
        TransformerLM(cfg), mesh, optax.adam(1e-2),
        TrainerConfig(data_axis="data", model_axis="model",
                      expert_axis="expert"))
    tokens = np.tile(np.arange(32, dtype=np.int32)[None], (8, 1))
    batch = {"tokens": tokens}
    state = trainer.init(jax.random.key(0), batch)
    print("expert weight sharding:",
          state["params"]["params"]["block_1"]["moe"]["w1"].sharding.spec)
    for step in range(5):
        state, loss = trainer.train_step(state, batch)
        print(f"  moe step {step}: loss {float(loss):.4f}")


def pipeline_training():
    mesh = spmd.create_mesh({"data": 2, "stage": 4})
    rng = np.random.RandomState(0)
    d = 32
    stacked = {
        "w": jnp.asarray(rng.randn(4, d, d) * 0.3, jnp.float32),
        "b": jnp.zeros((4, d), jnp.float32),
    }
    x = jnp.asarray(rng.randn(16, d), jnp.float32)
    target = jnp.asarray(rng.randn(16, d), jnp.float32)

    def block(p, h):
        return jnp.tanh(h @ p["w"] + p["b"])

    run = make_pipeline_apply(mesh, block, num_microbatches=4,
                              data_axis="data")
    grad = jax.grad(lambda p: jnp.mean((run(p, x) - target) ** 2))
    params = stacked
    for step in range(5):
        params = jax.tree_util.tree_map(
            lambda a, g: a - 0.5 * g, params, grad(params))
        loss = float(jnp.mean((run(params, x) - target) ** 2))
        print(f"  pipeline step {step}: loss {loss:.4f}")


if __name__ == "__main__":
    print(f"devices: {len(jax.devices())}")
    print("== dp x ep x tp (top-2 MoE) ==")
    moe_training()
    print("== dp x pp (GPipe) ==")
    pipeline_training()
