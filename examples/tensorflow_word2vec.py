"""Distributed skip-gram word2vec — the sparse-gradient showcase
(reference: examples/tensorflow_word2vec.py, re-founded TF2-eager).

The embedding lookup's gradient is a tf.IndexedSlices; the framework
routes it through the sparse path — allgather of (values, indices)
instead of a dense allreduce (reference:
horovod/tensorflow/__init__.py:72-83) — so only the rows each rank
actually touched cross the wire.

Run:  python -m horovod_tpu.run -np 2 python examples/tensorflow_word2vec.py

Synthetic corpus (Zipf-distributed token stream with local structure)
so the example runs hermetically.
"""

import argparse

import numpy as np
import tensorflow as tf

import horovod_tpu.tensorflow as hvd


def synthetic_corpus(rank: int, vocab: int, n: int = 20000):
    rng = np.random.RandomState(17 + rank)  # rank-sharded corpus
    # Zipfian unigram draws with short-range correlation: each token
    # is either fresh or a near-repeat of the previous one, giving
    # skip-gram pairs real signal.
    base = rng.zipf(1.3, n).clip(1, vocab - 1)
    prev = np.roll(base, 1)
    take_prev = rng.rand(n) < 0.3
    return np.where(take_prev, (prev + 1) % vocab, base).astype(np.int64)


def skipgram_batch(corpus, rng, batch, window=2):
    centers = rng.randint(window, len(corpus) - window, batch)
    offs = rng.randint(1, window + 1, batch) * \
        np.where(rng.rand(batch) < 0.5, 1, -1)
    return corpus[centers], corpus[centers + offs]


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--vocab", type=int, default=2000)
    p.add_argument("--dim", type=int, default=64)
    p.add_argument("--steps", type=int, default=200)
    p.add_argument("--batch-size", type=int, default=256)
    p.add_argument("--negatives", type=int, default=8)
    args = p.parse_args()

    hvd.init()
    rng = np.random.RandomState(1234 + hvd.rank())
    corpus = synthetic_corpus(hvd.rank(), args.vocab)

    emb = tf.Variable(tf.random.uniform(
        [args.vocab, args.dim], -0.05, 0.05, seed=7), name="emb")
    ctx = tf.Variable(tf.zeros([args.vocab, args.dim]), name="ctx")
    # Every rank starts identical (reference: broadcast_global_variables)
    hvd.broadcast_variables([emb, ctx], root_rank=0)

    opt = tf.keras.optimizers.SGD(0.5 * hvd.size())
    losses = []
    for step in range(args.steps):
        c, t = skipgram_batch(corpus, rng, args.batch_size)
        neg = rng.randint(1, args.vocab,
                          (args.batch_size, args.negatives))
        with tf.GradientTape() as tape:
            ce = tf.gather(emb, c)                      # [B, D]
            pos = tf.gather(ctx, t)                     # [B, D]
            ngs = tf.gather(ctx, neg)                   # [B, K, D]
            pos_logit = tf.reduce_sum(ce * pos, -1)
            neg_logit = tf.einsum("bd,bkd->bk", ce, ngs)
            loss = tf.reduce_mean(
                tf.nn.softplus(-pos_logit)
                + tf.reduce_sum(tf.nn.softplus(neg_logit), -1))
        grads = tape.gradient(loss, [emb, ctx])
        assert isinstance(grads[0], tf.IndexedSlices)   # the point!
        reduced = [hvd.allreduce(g, op=hvd.Average,
                                 name=f"w2v.g{i}.{step}")
                   for i, g in enumerate(grads)]
        opt.apply_gradients(zip(reduced, [emb, ctx]))
        losses.append(float(loss))

    if hvd.rank() == 0:
        k = max(1, args.steps // 10)
        print(f"loss {np.mean(losses[:k]):.4f} -> "
              f"{np.mean(losses[-k:]):.4f} over {args.steps} steps "
              f"({hvd.size()} rank(s), sparse IndexedSlices path)")
    hvd.shutdown()


if __name__ == "__main__":
    main()
