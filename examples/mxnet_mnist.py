"""MNIST-shaped training through the MXNet adapter
(reference: examples/mxnet_mnist.py — DistributedOptimizer wrapping an
mxnet optimizer, parameter broadcast from rank 0, metric averaging).

The model is a softmax regression with manually computed gradients so
the example exercises the adapter's exact contract — NDArray payloads
through ``broadcast_parameters``, gradient averaging inside
``DistributedOptimizer.update``, metric allreduce — independent of the
gluon autograd stack. With real mxnet installed it runs as-is; without
it (TPU images ship no mxnet wheel), demo mode uses the in-repo
NDArray-protocol double:

    HVD_FAKE_MXNET=1 python examples/mxnet_mnist.py
"""

import argparse
import os
import sys

import numpy as np


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=60)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--lr", type=float, default=0.1)
    args = p.parse_args()

    if os.environ.get("HVD_FAKE_MXNET") == "1":
        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        from tests import fake_mxnet
        fake_mxnet.install()

    import mxnet as mx
    import horovod_tpu.mxnet as hvd

    hvd.init()
    rng = np.random.RandomState(100 + hvd.rank())

    # synthetic MNIST shard per rank; each class lights up one pixel so
    # the model has a clear signal to learn
    n = 1024
    x = rng.rand(n, 784).astype(np.float32)
    y = rng.randint(0, 10, n).astype(np.int64)
    x[np.arange(n), y] += 2.0

    w = mx.nd.array(np.zeros((784, 10), np.float32))
    b = mx.nd.array(np.zeros((10,), np.float32))

    class SGD:
        """Minimal mxnet-style optimizer: update(index, weight, grad,
        state) applies one step in place."""

        def update(self, index, weight, grad, state):
            weight[:] = weight.asnumpy() - args.lr * grad.asnumpy()

    # gradient averaging across ranks happens inside update()
    opt = hvd.DistributedOptimizer(SGD())
    # rank 0's initialization becomes everyone's
    hvd.broadcast_parameters({"w": w, "b": b}, root_rank=0)

    def forward_backward(xb, yb):
        logits = xb @ w.asnumpy() + b.asnumpy()
        logits -= logits.max(axis=1, keepdims=True)
        p = np.exp(logits)
        p /= p.sum(axis=1, keepdims=True)
        loss = -np.log(p[np.arange(len(yb)), yb] + 1e-9).mean()
        p[np.arange(len(yb)), yb] -= 1.0
        p /= len(yb)
        return loss, xb.T @ p, p.sum(axis=0)

    first = last = None
    for step in range(args.steps):
        lo = (step * args.batch_size) % max(n - args.batch_size, 1)
        xb, yb = x[lo:lo + args.batch_size], y[lo:lo + args.batch_size]
        loss, dw, db = forward_backward(xb, yb)
        opt.update(0, w, mx.nd.array(dw), None)
        opt.update(1, b, mx.nd.array(db), None)
        if step == 0:
            first = loss
        last = loss

    # epoch metric averaged over ranks (MetricAverage analog)
    avg = hvd.allreduce(mx.nd.array(np.asarray([last], np.float64)),
                        average=True, name="metric.loss")
    if hvd.rank() == 0:
        print(f"loss {first:.4f} -> {float(avg.asnumpy()[0]):.4f} "
              f"over {args.steps} steps on {hvd.size()} rank(s)")
    assert last < first, "model learned nothing"
    hvd.shutdown()


if __name__ == "__main__":
    main()
