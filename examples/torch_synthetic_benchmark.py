"""Synthetic benchmark through the torch adapter
(reference: examples/pytorch_synthetic_benchmark.py — img/sec mean
± 1.96σ per device and total, --fp16-allreduce flag, warmup +
batches-per-iter × iters timing shape).

Measures the framework's HOST gradient path (torch CPU tensors staged
through the background runtime's negotiated collectives); the
TPU-compute benchmark with the same timing discipline is
examples/jax_synthetic_benchmark.py / bench.py.

Run:  python -m horovod_tpu.run -np 2 python \
          examples/torch_synthetic_benchmark.py --model resnet50tiny
"""

import argparse
import timeit

import numpy as np
import torch
import torch.nn.functional as F

import horovod_tpu.torch as hvd
from torch_imagenet_resnet50 import ResNet50


def build_model(name: str):
    if name == "resnet50":
        return ResNet50(num_classes=1000), 224
    if name == "resnet50tiny":
        # smoke-test scale: same topology, 1/8 width, small images
        return ResNet50(num_classes=10, width=8), 32
    raise SystemExit(f"unknown --model {name}")


def main():
    p = argparse.ArgumentParser(
        description="torch synthetic benchmark (horovod_tpu)")
    p.add_argument("--model", default="resnet50")
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--fp16-allreduce", action="store_true")
    p.add_argument("--num-warmup-batches", type=int, default=10)
    p.add_argument("--num-batches-per-iter", type=int, default=10)
    p.add_argument("--num-iters", type=int, default=10)
    args = p.parse_args()

    hvd.init()
    torch.manual_seed(42)
    model, image_size = build_model(args.model)

    optimizer = torch.optim.SGD(model.parameters(), lr=0.01)
    compression = (hvd.Compression.fp16 if args.fp16_allreduce
                   else hvd.Compression.none)
    optimizer = hvd.DistributedOptimizer(
        optimizer, named_parameters=model.named_parameters(),
        compression=compression)
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)

    data = torch.randn(args.batch_size, 3, image_size, image_size)
    target = torch.randint(0, 10, (args.batch_size,))

    def benchmark_step():
        optimizer.zero_grad()
        loss = F.cross_entropy(model(data), target)
        loss.backward()
        optimizer.step()

    if hvd.rank() == 0:
        print(f"Model: {args.model}, batch size {args.batch_size}, "
              f"{hvd.size()} process(es)")
    timeit.timeit(benchmark_step, number=args.num_warmup_batches)

    img_secs = []
    for i in range(args.num_iters):
        t = timeit.timeit(benchmark_step,
                          number=args.num_batches_per_iter)
        img_sec = args.batch_size * args.num_batches_per_iter / t
        if hvd.rank() == 0:
            print(f"Iter #{i}: {img_sec:.1f} img/sec per process")
        img_secs.append(img_sec)

    # mean ± 1.96 sigma, per process and total, like the reference
    img_sec_mean = np.mean(img_secs)
    img_sec_conf = 1.96 * np.std(img_secs)
    total = hvd.allreduce(torch.tensor(img_sec_mean), op=hvd.Sum,
                          name="bench.total").item()
    if hvd.rank() == 0:
        print(f"Img/sec per process: {img_sec_mean:.1f} "
              f"+-{img_sec_conf:.1f}")
        print(f"Total img/sec on {hvd.size()} process(es): "
              f"{total:.1f}")
    hvd.shutdown()


if __name__ == "__main__":
    main()
