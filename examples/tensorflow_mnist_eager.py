"""Eager-mode MNIST with DistributedGradientTape
(reference: examples/tensorflow_mnist_eager.py — per-step tape
gradients wrapped by hvd.DistributedGradientTape, rank-0 checkpointing,
first-batch broadcast of variables).

Run:  python -m horovod_tpu.run -np 2 python \
          examples/tensorflow_mnist_eager.py
"""

import argparse

import numpy as np
import tensorflow as tf

import horovod_tpu.tensorflow as hvd


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=40)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--lr", type=float, default=0.001)
    args = p.parse_args()

    hvd.init()
    tf.random.set_seed(42)

    model = tf.keras.Sequential([
        tf.keras.layers.Input((28, 28, 1)),
        tf.keras.layers.Conv2D(16, 3, activation="relu"),
        tf.keras.layers.MaxPooling2D(2),
        tf.keras.layers.Flatten(),
        tf.keras.layers.Dense(64, activation="relu"),
        tf.keras.layers.Dense(10),
    ])
    # lr scaled by world size (reference: opt scaling)
    opt = tf.keras.optimizers.Adam(args.lr * hvd.size())

    rng = np.random.RandomState(100 + hvd.rank())
    x_all = rng.rand(1024, 28, 28, 1).astype(np.float32)
    y_all = rng.randint(0, 10, 1024).astype(np.int64)
    # each class lights up one pixel so there is a real signal to learn
    x_all[np.arange(1024), 0, y_all, 0] += 3.0

    first_loss = last_loss = None
    for step in range(args.steps):
        lo = (step * args.batch_size) % max(1024 - args.batch_size, 1)
        x = tf.constant(x_all[lo:lo + args.batch_size])
        y = tf.constant(y_all[lo:lo + args.batch_size])
        with tf.GradientTape() as tape:
            logits = model(x, training=True)
            loss = tf.reduce_mean(
                tf.nn.sparse_softmax_cross_entropy_with_logits(
                    labels=y, logits=logits))
        # per-step gradient averaging across ranks
        tape = hvd.DistributedGradientTape(tape)
        grads = tape.gradient(loss, model.trainable_variables)
        opt.apply_gradients(zip(grads, model.trainable_variables))
        if step == 0:
            # rank 0's initial state becomes everyone's, AFTER the
            # first apply so optimizer slots exist (reference:
            # tensorflow_mnist_eager.py broadcast-on-first-batch)
            hvd.broadcast_variables(model.variables, root_rank=0)
            hvd.broadcast_variables(opt.variables, root_rank=0)
            first_loss = float(loss)
        if step % 10 == 0 and hvd.rank() == 0:
            print(f"step {step}: loss {float(loss):.4f}")
        last_loss = float(loss)

    if hvd.rank() == 0:
        print(f"loss {first_loss:.4f} -> {last_loss:.4f} over "
              f"{args.steps} steps")
    hvd.shutdown()


if __name__ == "__main__":
    main()
