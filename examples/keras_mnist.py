"""MNIST with the Keras adapter (reference: examples/keras_mnist.py).

Run:  python -m horovod_tpu.run -np 2 python examples/keras_mnist.py
"""

import numpy as np
import keras

import horovod_tpu.keras as hvd


def main():
    hvd.init()
    keras.utils.set_random_seed(42)

    model = keras.Sequential([
        keras.layers.Input((28, 28, 1)),
        keras.layers.Conv2D(32, (3, 3), activation="relu"),
        keras.layers.MaxPooling2D((2, 2)),
        keras.layers.Flatten(),
        keras.layers.Dense(128, activation="relu"),
        keras.layers.Dense(10, activation="softmax"),
    ])

    # lr scaled by size + distributed optimizer (reference:
    # examples/keras_mnist.py opt scaling + hvd.DistributedOptimizer)
    opt = keras.optimizers.Adadelta(1.0 * hvd.size())
    model.compile(loss="sparse_categorical_crossentropy",
                  optimizer=hvd.DistributedOptimizer(opt),
                  metrics=["accuracy"])

    callbacks = [
        hvd.callbacks.BroadcastGlobalVariablesCallback(0),
        hvd.callbacks.MetricAverageCallback(),
        hvd.callbacks.LearningRateWarmupCallback(warmup_epochs=1),
    ]

    rng = np.random.RandomState(hvd.rank())
    x = rng.rand(512, 28, 28, 1).astype(np.float32)
    y = rng.randint(0, 10, 512)

    model.fit(x, y, batch_size=64, epochs=2, callbacks=callbacks,
              verbose=1 if hvd.rank() == 0 else 0)
    hvd.shutdown()


if __name__ == "__main__":
    main()
