"""MNIST with the TensorFlow eager adapter
(reference: examples/tensorflow_mnist_eager.py).

Run:  python -m horovod_tpu.run -np 2 python examples/tensorflow_mnist.py
"""

import argparse

import numpy as np
import tensorflow as tf

import horovod_tpu.tensorflow as hvd


def build_model():
    return tf.keras.Sequential([
        tf.keras.layers.Input((28, 28, 1)),
        tf.keras.layers.Conv2D(10, 5, activation="relu"),
        tf.keras.layers.MaxPool2D(2),
        tf.keras.layers.Conv2D(20, 5, activation="relu"),
        tf.keras.layers.MaxPool2D(2),
        tf.keras.layers.Flatten(),
        tf.keras.layers.Dense(50, activation="relu"),
        tf.keras.layers.Dense(10),
    ])


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--epochs", type=int, default=2)
    parser.add_argument("--batch-size", type=int, default=64)
    parser.add_argument("--lr", type=float, default=0.001)
    parser.add_argument("--fp16-allreduce", action="store_true")
    args = parser.parse_args()

    hvd.init()
    tf.random.set_seed(42)

    model = build_model()
    # lr scaled by world size (reference: tensorflow_mnist_eager.py)
    opt = tf.keras.optimizers.Adam(args.lr * hvd.size())
    compression = (hvd.Compression.fp16 if args.fp16_allreduce
                   else hvd.Compression.none)
    loss_fn = tf.keras.losses.SparseCategoricalCrossentropy(
        from_logits=True)

    rng = np.random.RandomState(100 + hvd.rank())  # sharded data
    x = rng.rand(512, 28, 28, 1).astype(np.float32)
    y = rng.randint(0, 10, 512).astype(np.int64)

    first_batch = True
    steps = len(x) // args.batch_size
    for epoch in range(args.epochs):
        for i in range(steps):
            sl = slice(i * args.batch_size, (i + 1) * args.batch_size)
            with tf.GradientTape() as tape:
                logits = model(x[sl], training=True)
                loss = loss_fn(y[sl], logits)
            # the framework's gradient path: every grad allreduced
            tape = hvd.DistributedGradientTape(tape,
                                               compression=compression)
            grads = tape.gradient(loss, model.trainable_variables)
            opt.apply_gradients(zip(grads, model.trainable_variables))
            if first_batch:
                # after the first step so optimizer slots exist
                # (reference: tensorflow_mnist_eager.py broadcast on
                # first batch)
                hvd.broadcast_variables(model.variables, root_rank=0)
                hvd.broadcast_variables(opt.variables, root_rank=0)
                first_batch = False
        if hvd.rank() == 0:
            print(f"epoch {epoch}: loss {float(loss):.4f}")
    hvd.shutdown()


if __name__ == "__main__":
    main()
