"""Build script — compiles the native core as a C extension.

Role-equivalent of the reference's 1,012-line setup.py
(reference: setup.py:32-36 five framework extensions, 298-522 MPI/CUDA
/NCCL/DDL probing). The TPU build needs none of that probing: one
dependency-free C++ translation unit, built here as an auxiliary
shared library (ctypes-loaded, no Python ABI coupling). If no compiler
is available the install still succeeds — every native path has a
pure-Python fallback (horovod_tpu/native.py).
"""

import os
import subprocess

from setuptools import setup
from setuptools.command.build_py import build_py


class BuildWithNative(build_py):
    def run(self):
        here = os.path.dirname(os.path.abspath(__file__))
        native = os.path.join(here, "native")
        if os.path.isdir(native):
            try:
                subprocess.run(["make", "-C", native, "-s"], check=True,
                               timeout=300)
            except (subprocess.SubprocessError, FileNotFoundError) as e:
                print(f"warning: native core build skipped ({e}); "
                      "pure-Python paths will be used")
        super().run()


setup(cmdclass={"build_py": BuildWithNative})
