// C API of the native runtime core, loaded from Python via ctypes.
//
// TPU-native counterpart of the reference's C++ core surface
// (reference: horovod/common/operations.cc C API 1371-1426 and the
// transport/fusion internals behind it). The Python runtime calls
// these for the per-cycle hot paths; every entry point has a
// pure-Python fallback so the framework runs without the library.
//
// Frame format (must match horovod_tpu/common/network.py Channel):
//   u32le payload_len | u8 tag | [32-byte HMAC-SHA256(tag|payload)] |
//   payload
#pragma once

#include <cstdint>
#include <cstddef>

extern "C" {

// ---- frame transport (control plane batch ops) -----------------------
// All functions return 0 on success, negative errno-style codes on
// failure. Sockets are plain connected fds owned by Python.

// Read one frame from each of n fds (poll-driven, GIL released on the
// Python side). For fd i: *(bufs+i) receives a malloc'd payload whose
// length is written to lens[i]; tags[i] receives the frame tag.
// Caller frees each buffer with hvd_free. ``arrive`` (nullable, n
// doubles) receives each frame's completion stamp on CLOCK_MONOTONIC
// — the same clock Python's time.monotonic() reads — feeding the
// coordinator's per-cycle straggler attribution (common/trace.py);
// slots of peers whose frame never arrived are left untouched.
int hvd_gather_frames(const int* fds, int n, const uint8_t* secret,
                      int secret_len, uint8_t** bufs, int64_t* lens,
                      uint8_t* tags, int timeout_ms, double* arrive);

// Write the same frame to each of n fds.
int hvd_broadcast_frame(const int* fds, int n, uint8_t tag,
                        const uint8_t* payload, int64_t len,
                        const uint8_t* secret, int secret_len);

// Write a distinct frame to each fd (scatter).
int hvd_scatter_frames(const int* fds, int n, uint8_t tag,
                       const uint8_t* const* payloads,
                       const int64_t* lens, const uint8_t* secret,
                       int secret_len);

void hvd_free(uint8_t* buf);

// ---- zero-copy data plane (vectored wire I/O) ------------------------
// One framed send assembled from scatter-gather parts: the header,
// optional HMAC digest and every payload iovec go out through looped
// sendmsg(2) straight from caller memory (numpy buffer pointers) —
// no intermediate bytes object is ever materialized. Payload length
// is sum(lens).
int hvd_sendv(int fd, uint8_t tag, const void* const* bufs,
              const int64_t* lens, int niov,
              const uint8_t* secret, int secret_len);

// Receive one frame with the payload landing directly in caller
// memory. Frames whose tag appears in skip_tags (liveness beacons,
// stray metrics) are drained, authenticated and discarded without
// touching buf. Returns 0 with payload in buf (len/tag out-params);
// 1 when the payload did not fit cap — it is then returned complete
// via *spill (malloc'd; caller frees with hvd_free) so no frame is
// ever lost; negative errno on transport failure. timeout_ms >= 0
// arms a total-silence deadline sliced into interval_ms polls (any
// received byte resets the clock — same semantics as Channel.arm);
// timeout_ms < 0 blocks forever.
int hvd_recv_into(int fd, const uint8_t* secret, int secret_len,
                  void* buf, int64_t cap,
                  const uint8_t* skip_tags, int nskip,
                  int64_t* out_len, uint8_t* out_tag,
                  int timeout_ms, int interval_ms,
                  uint8_t** spill);

// ---- batched-submission reactor (kernel-side wire speed) -------------
// One batched gather replacing the coordinator's N sequential
// hvd_recv_into calls: every pending peer's DATA frame is awaited in
// a single readiness loop (io_uring when the Makefile probe compiled
// it in AND the running kernel accepts io_uring_setup; a poll(2)
// batch otherwise — the bytes read and written are identical either
// way, only how readiness is learned differs). Frames whose tag
// appears in skip_tags (PING) are drained, authenticated and
// discarded in C without bouncing to Python. ``done`` (n bytes,
// in/out) marks peers already absorbed, so the caller re-enters with
// progress intact after handling a deviation. Deviations (METRICS /
// TRACE / ABORT / wrong tag / payload overflowing caps[i]) return 1
// with the whole authenticated frame in *dev_buf (malloc'd, caller
// frees with hvd_free) and the peer index in *dev_idx. Transport
// errors return negative errno with *dev_idx naming the failing peer
// (-1 for a world-wide condition such as -ETIMEDOUT after timeout_ms
// of total silence across every peer). on_idle (nullable) fires once
// per idle poll slice (the coordinator's PING fan-out); ``arrive``
// (nullable, n doubles) receives per-peer completion stamps on
// CLOCK_MONOTONIC for straggler attribution. batch_sizes/nbatches
// (nullable pair, capacity n): the number of frames completed by each
// wakeup that completed at least one — the reactor's batching
// histogram.
int hvd_gather_frames_batched(const int* fds, int n,
                              const uint8_t* secret, int secret_len,
                              uint8_t want_tag, void* const* bufs,
                              const int64_t* caps, int64_t* lens,
                              const uint8_t* skip_tags, int nskip,
                              int timeout_ms, int interval_ms,
                              void (*on_idle)(void),
                              uint8_t* done, double* arrive,
                              int32_t* batch_sizes, int* nbatches,
                              int* dev_idx, uint8_t** dev_buf,
                              int64_t* dev_len, uint8_t* dev_tag);

// hvd_sendv with MSG_ZEROCOPY: same frame bytes on the wire, but
// payload iovecs are pinned by the kernel instead of copied into the
// socket buffer, and the completion notifications are drained from
// the error queue BEFORE returning (the caller may mutate or free the
// buffers the moment this returns, so lingering references are not
// allowed). *zc_sends counts sendmsg calls that went out zero-copy;
// *zc_copied counts completions where the kernel fell back to a copy
// (loopback always does — the counters surface the degradation).
// Falls back internally to the plain copying send when the socket
// family or kernel lacks SO_ZEROCOPY, or per-call on ENOBUFS.
int hvd_sendv_zc(int fd, uint8_t tag, const void* const* bufs,
                 const int64_t* lens, int niov,
                 const uint8_t* secret, int secret_len,
                 int timeout_ms, int* zc_sends, int* zc_copied);

// Chunked cut-through relay (the hierarchical root/leaf legs and the
// ServiceGate snapshot fanout): read one frame from up_fd and forward
// it to every child fd chunk-by-chunk as it arrives — the header and
// digest go downstream before the first payload byte, so a child's
// read of chunk i overlaps the relay's read of chunk i+1 (the
// hvd_steady_worker_chunked discipline applied to the relay), instead
// of the classic store-and-forward that buffered the whole payload
// first. Children re-verify the digest themselves; the relay also
// authenticates incrementally and returns -EBADMSG after the last
// chunk on mismatch. Frames whose tag is in skip_tags are drained and
// discarded (not relayed). Returns 0 with the payload in buf; 1 when
// it overflowed cap (complete in *spill, malloc'd, already relayed);
// 2 for a non-skip deviation (PING/ABORT/wrong tag — NOT relayed,
// whole frame in *spill with out_len/out_tag set, caller decides).
int hvd_relay_frame(int up_fd, const int* child_fds, int nchild,
                    uint8_t want_tag, void* buf, int64_t cap,
                    const uint8_t* secret, int secret_len,
                    const uint8_t* skip_tags, int nskip,
                    int64_t chunk_bytes, int timeout_ms,
                    int interval_ms, int64_t* out_len,
                    uint8_t* out_tag, uint8_t** spill);

// Build/runtime capability flags: bit 0 = compiled with io_uring
// support (Makefile probe), bit 1 = the running kernel accepted
// io_uring_setup (runtime probe, cached), bit 2 = MSG_ZEROCOPY send
// path compiled in. Surfaced through hvd_build_info.
int hvd_build_flags(void);

// ---- native steady replay (the fused speculative cycle in C) ---------
// One steady-state training step without re-entering Python per frame:
// both halves speak the exact CACHED_SPEC wire layout of
// common/wire.py (u8 kind | i64 epoch | u32 nslots | mask |
// u32 nseg | nseg x (u8 dtype | i64 nbytes | raw)), so native and
// pure-Python ranks interoperate frame-for-frame. ``prefix`` is the
// constant region up to the first segment header (request hit-mask ==
// response grant-mask in a granted steady cycle, so one prefix serves
// both directions); seg_hdrs are the constant 9-byte per-segment
// headers. Any frame that deviates from the expected layout is
// returned whole to Python via dev_buf/dev_len/dev_tag (return 1) and
// the caller resumes the classic path — deviation is a fallback, not
// an error. Return 0 on a completed cycle, negative errno otherwise
// (-ETIMEDOUT after timeout_ms of total silence).

// Worker half: send the speculative request frame (prefix + per-seg
// header/data iovecs from the fusion arena), then receive the world-
// reduced response straight into recv_ptrs.
int hvd_steady_worker(int fd, uint8_t req_tag, uint8_t resp_tag,
                      const uint8_t* prefix, int64_t prefix_len,
                      const uint8_t* const* seg_hdrs,
                      const int64_t* seg_hdr_lens,
                      const void* const* send_ptrs,
                      void* const* recv_ptrs,
                      const int64_t* seg_lens, int nseg,
                      const uint8_t* secret, int secret_len,
                      const uint8_t* skip_tags, int nskip,
                      int timeout_ms, int interval_ms,
                      uint8_t** dev_buf, int64_t* dev_len,
                      uint8_t* dev_tag);

// Chunked-pipelined worker half (the overlap tier's transfer stage,
// HOROVOD_OVERLAP_CHUNK_BYTES): same frame, same wire bytes, but
// compressed segments are cast from their full-precision staging
// buffers chunk-by-chunk interleaved with the send — compression of
// chunk i+1 overlaps the kernel-buffered transmission of chunk i
// (with frame auth armed the cast and HMAC fuse into one cache-warm
// pass and the frame then goes out in one vectored send, since the
// digest must precede the payload). stage_ptrs[j] == NULL means
// segment j is pre-cast in send_ptrs[j] (stage_codes[j] = -1);
// wire_codes give each segment's on-wire dtype (hvd_cast codes).
// Receive half and return contract identical to hvd_steady_worker.
int hvd_steady_worker_chunked(int fd, uint8_t req_tag, uint8_t resp_tag,
                              const uint8_t* prefix, int64_t prefix_len,
                              const uint8_t* const* seg_hdrs,
                              const int64_t* seg_hdr_lens,
                              const void* const* send_ptrs,
                              const void* const* stage_ptrs,
                              const int* stage_codes,
                              int64_t chunk_bytes,
                              void* const* recv_ptrs,
                              const int64_t* seg_lens,
                              const int* wire_codes, int nseg,
                              const uint8_t* secret, int secret_len,
                              const uint8_t* skip_tags, int nskip,
                              int timeout_ms, int interval_ms,
                              uint8_t** dev_buf, int64_t* dev_len,
                              uint8_t* dev_tag);

// Coordinator half: poll-gather one speculative frame per peer
// (payload must match prefix/seg_hdrs byte-for-byte; segment data
// lands in peer_seg_ptrs[i*nseg + j]), reduce every peer's segments
// into acc_ptrs (pre-filled with rank 0's own contribution; dtype
// codes as for hvd_sum_into), then broadcast the response frame from
// the accumulators. ``done`` (n bytes, in/out) marks peers whose
// frame was already absorbed — on a deviation (rc 1, *dev_idx = peer
// index) or an out-of-band bounce the caller can hand the array back
// and resume, or fall back with the absorbed frames intact.
// on_idle (nullable) fires once per idle poll slice (PING fan-out).
// ``arrive`` (nullable, n doubles) mirrors hvd_gather_frames: each
// peer's speculative-frame completion stamp on CLOCK_MONOTONIC, so
// the steady fast path stays visible to straggler attribution.
int hvd_steady_coord(const int* fds, int n, uint8_t req_tag,
                     uint8_t resp_tag,
                     const uint8_t* prefix, int64_t prefix_len,
                     const uint8_t* const* seg_hdrs,
                     const int64_t* seg_hdr_lens,
                     const int64_t* seg_lens, const int* seg_dtypes,
                     int nseg,
                     uint8_t* const* peer_seg_ptrs,
                     void* const* acc_ptrs,
                     const uint8_t* secret, int secret_len,
                     const uint8_t* skip_tags, int nskip,
                     int timeout_ms, int interval_ms,
                     void (*on_idle)(void),
                     uint8_t* done, double* arrive,
                     int* dev_idx, uint8_t** dev_buf,
                     int64_t* dev_len, uint8_t* dev_tag);

// ---- fusion buffer pack/unpack ---------------------------------------
// (reference: horovod/common/ops/collective_operations.cc:35-63
//  MemcpyInFusionBuffer / MemcpyOutFusionBuffer)
void hvd_pack(const void* const* srcs, const int64_t* nbytes, int n,
              void* dst);
void hvd_unpack(const void* src, const int64_t* nbytes, int n,
                void* const* dsts);

// Elementwise sum into acc (the coordinator-side reduction of the
// socket backend). dtype codes: 0=f32 1=f64 2=i32 3=i64 4=u8 5=f16raw
// (f16 summed via f32 conversion; reference: common/half.cc:42-77).
int hvd_sum_into(void* acc, const void* src, int64_t count, int dtype);

// Elementwise dtype cast (the wire-compression leg: gradients are
// compressed into the fusion arena on send and decompressed into
// fresh outputs on receive). Supported pairs: f32<->bf16 (0<->6) and
// f32<->f16 (0<->5); anything else returns -EINVAL and the caller
// uses the numpy fallback. src and dst must not overlap.
int hvd_cast(const void* src, void* dst, int64_t count, int src_dtype,
             int dst_dtype);

// ---- native int8 codec (wire_dtype WIRE_INT8 without numpy) ----------
// Quantize count f32/f64 lanes (dtype 0=f32 1=f64) into the int8 wire
// layout [f32 scale | count x int8]: scale = max|x| / 127 narrowed to
// f32, lanes = clip(rint(x / scale), -127, 127) — bit-identical to
// the numpy reference in common/wire_dtype.py (round-half-even via
// rint, scalar narrowed to the array dtype before the multiply, clamp
// before the int8 cast). Error feedback fuses into the same pass:
// residual (nullable) is added lane-wise before scanning, and
// residual_out (nullable; required when residual is set, may alias
// residual) receives compensated - dequantized. out must hold
// 4 + count bytes.
int hvd_quant8(const void* src, int64_t count, int dtype,
               const void* residual, void* residual_out, uint8_t* out);

// Inverse: expand [f32 scale | count x int8] into count f32/f64 lanes
// (out[i] = lane * scale, the scale widened/kept per dtype exactly as
// the numpy reference does).
int hvd_dequant8(const uint8_t* src, int64_t count, int dtype,
                 void* out);

// ---- self-test helpers ----------------------------------------------
// HMAC-SHA256 of (tag|payload) into out[32] — lets Python verify the
// embedded SHA implementation against hashlib.
void hvd_hmac_sha256(const uint8_t* key, int key_len, uint8_t tag,
                     const uint8_t* payload, int64_t len, uint8_t* out);

}  // extern "C"
