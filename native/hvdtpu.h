// C API of the native runtime core, loaded from Python via ctypes.
//
// TPU-native counterpart of the reference's C++ core surface
// (reference: horovod/common/operations.cc C API 1371-1426 and the
// transport/fusion internals behind it). The Python runtime calls
// these for the per-cycle hot paths; every entry point has a
// pure-Python fallback so the framework runs without the library.
//
// Frame format (must match horovod_tpu/common/network.py Channel):
//   u32le payload_len | u8 tag | [32-byte HMAC-SHA256(tag|payload)] |
//   payload
#pragma once

#include <cstdint>
#include <cstddef>

extern "C" {

// ---- frame transport (control plane batch ops) -----------------------
// All functions return 0 on success, negative errno-style codes on
// failure. Sockets are plain connected fds owned by Python.

// Read one frame from each of n fds (poll-driven, GIL released on the
// Python side). For fd i: *(bufs+i) receives a malloc'd payload whose
// length is written to lens[i]; tags[i] receives the frame tag.
// Caller frees each buffer with hvd_free.
int hvd_gather_frames(const int* fds, int n, const uint8_t* secret,
                      int secret_len, uint8_t** bufs, int64_t* lens,
                      uint8_t* tags, int timeout_ms);

// Write the same frame to each of n fds.
int hvd_broadcast_frame(const int* fds, int n, uint8_t tag,
                        const uint8_t* payload, int64_t len,
                        const uint8_t* secret, int secret_len);

// Write a distinct frame to each fd (scatter).
int hvd_scatter_frames(const int* fds, int n, uint8_t tag,
                       const uint8_t* const* payloads,
                       const int64_t* lens, const uint8_t* secret,
                       int secret_len);

void hvd_free(uint8_t* buf);

// ---- fusion buffer pack/unpack ---------------------------------------
// (reference: horovod/common/ops/collective_operations.cc:35-63
//  MemcpyInFusionBuffer / MemcpyOutFusionBuffer)
void hvd_pack(const void* const* srcs, const int64_t* nbytes, int n,
              void* dst);
void hvd_unpack(const void* src, const int64_t* nbytes, int n,
                void* const* dsts);

// Elementwise sum into acc (the coordinator-side reduction of the
// socket backend). dtype codes: 0=f32 1=f64 2=i32 3=i64 4=u8 5=f16raw
// (f16 summed via f32 conversion; reference: common/half.cc:42-77).
int hvd_sum_into(void* acc, const void* src, int64_t count, int dtype);

// ---- self-test helpers ----------------------------------------------
// HMAC-SHA256 of (tag|payload) into out[32] — lets Python verify the
// embedded SHA implementation against hashlib.
void hvd_hmac_sha256(const uint8_t* key, int key_len, uint8_t tag,
                     const uint8_t* payload, int64_t len, uint8_t* out);

}  // extern "C"
