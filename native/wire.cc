// Wire codec — C++ implementation of the control-plane message
// encoding (role-equivalent of the reference's FlatBuffers layer,
// reference: horovod/common/wire/message.fbs + message.cc:122-215).
//
// The layout is defined in horovod_tpu/common/wire.py; this file
// implements the identical encoding in C++ (parse into structs,
// serialize back), byte-for-byte — tests/test_native.py proves
// round-trip parity on randomized messages. The structs are the
// C++ core's view of Request/Response for future in-core negotiation.

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

namespace {

struct Request {
  uint8_t request_type;
  int32_t request_rank;
  uint8_t tensor_type;
  int32_t root_rank;
  int32_t device;
  std::string tensor_name;
  double prescale;
  double postscale;
  std::vector<int64_t> shape;
};

struct RequestList {
  bool shutdown;
  std::vector<Request> requests;
};

struct Response {
  uint8_t response_type;
  std::string error_message;
  double prescale;
  double postscale;
  std::vector<std::string> tensor_names;
  std::vector<int32_t> devices;
  std::vector<int64_t> tensor_sizes;
};

struct ResponseList {
  bool shutdown;
  double tuned_cycle_time_ms;
  int64_t tuned_fusion_threshold_bytes;
  std::vector<Response> responses;
};

class Reader {
 public:
  Reader(const uint8_t* p, int64_t n) : p_(p), n_(n) {}
  bool ok() const { return ok_; }

  uint8_t u8() {
    if (!need(1)) return 0;
    return p_[off_++];
  }
  uint32_t u32() {
    if (!need(4)) return 0;
    uint32_t v;
    memcpy(&v, p_ + off_, 4);
    off_ += 4;
    return v;
  }
  int32_t i32() {
    if (!need(4)) return 0;
    int32_t v;
    memcpy(&v, p_ + off_, 4);
    off_ += 4;
    return v;
  }
  int64_t i64() {
    if (!need(8)) return 0;
    int64_t v;
    memcpy(&v, p_ + off_, 8);
    off_ += 8;
    return v;
  }
  double f64() {
    if (!need(8)) return 0;
    double v;
    memcpy(&v, p_ + off_, 8);
    off_ += 8;
    return v;
  }
  std::string str() {
    uint32_t n = u32();
    if (!need(n)) return "";
    std::string s(reinterpret_cast<const char*>(p_ + off_), n);
    off_ += n;
    return s;
  }
  bool done() const { return ok_ && off_ == n_; }

 private:
  bool need(int64_t k) {
    if (!ok_ || off_ + k > n_) {
      ok_ = false;
      return false;
    }
    return true;
  }
  const uint8_t* p_;
  int64_t n_;
  int64_t off_ = 0;
  bool ok_ = true;
};

class Writer {
 public:
  void u8(uint8_t v) { buf_.push_back(v); }
  void u32(uint32_t v) { raw(&v, 4); }
  void i32(int32_t v) { raw(&v, 4); }
  void i64(int64_t v) { raw(&v, 8); }
  void f64(double v) { raw(&v, 8); }
  void str(const std::string& s) {
    u32(uint32_t(s.size()));
    buf_.insert(buf_.end(), s.begin(), s.end());
  }
  uint8_t* release(int64_t* out_len) {
    auto* out = static_cast<uint8_t*>(malloc(buf_.size() ? buf_.size() : 1));
    if (out) memcpy(out, buf_.data(), buf_.size());
    *out_len = int64_t(buf_.size());
    return out;
  }

 private:
  void raw(const void* p, size_t k) {
    const uint8_t* b = static_cast<const uint8_t*>(p);
    buf_.insert(buf_.end(), b, b + k);
  }
  std::vector<uint8_t> buf_;
};

bool parse_request(Reader& r, Request* req) {
  req->request_type = r.u8();
  req->request_rank = r.i32();
  req->tensor_type = r.u8();
  req->root_rank = r.i32();
  req->device = r.i32();
  req->tensor_name = r.str();
  req->prescale = r.f64();
  req->postscale = r.f64();
  uint8_t ndim = r.u8();
  req->shape.clear();
  for (int i = 0; i < ndim; i++) req->shape.push_back(r.i64());
  return r.ok();
}

void write_request(Writer& w, const Request& req) {
  w.u8(req.request_type);
  w.i32(req.request_rank);
  w.u8(req.tensor_type);
  w.i32(req.root_rank);
  w.i32(req.device);
  w.str(req.tensor_name);
  w.f64(req.prescale);
  w.f64(req.postscale);
  w.u8(uint8_t(req.shape.size()));
  for (int64_t d : req.shape) w.i64(d);
}

bool parse_response(Reader& r, Response* resp) {
  resp->response_type = r.u8();
  resp->error_message = r.str();
  resp->prescale = r.f64();
  resp->postscale = r.f64();
  uint32_t n = r.u32();
  resp->tensor_names.clear();
  for (uint32_t i = 0; r.ok() && i < n; i++)
    resp->tensor_names.push_back(r.str());
  n = r.u32();
  resp->devices.clear();
  for (uint32_t i = 0; r.ok() && i < n; i++)
    resp->devices.push_back(r.i32());
  n = r.u32();
  resp->tensor_sizes.clear();
  for (uint32_t i = 0; r.ok() && i < n; i++)
    resp->tensor_sizes.push_back(r.i64());
  return r.ok();
}

void write_response(Writer& w, const Response& resp) {
  w.u8(resp.response_type);
  w.str(resp.error_message);
  w.f64(resp.prescale);
  w.f64(resp.postscale);
  w.u32(uint32_t(resp.tensor_names.size()));
  for (const auto& s : resp.tensor_names) w.str(s);
  w.u32(uint32_t(resp.devices.size()));
  for (int32_t d : resp.devices) w.i32(d);
  w.u32(uint32_t(resp.tensor_sizes.size()));
  for (int64_t s : resp.tensor_sizes) w.i64(s);
}

}  // namespace

extern "C" {

// Parse and re-serialize a RequestList; byte-identical output proves
// the C++ structs capture the full encoding. Returns 0 on success;
// -1 on malformed input (including trailing bytes). Caller frees
// *out with hvd_free.
int hvd_wire_reencode_request_list(const uint8_t* in, int64_t len,
                                   uint8_t** out, int64_t* out_len) {
  Reader r(in, len);
  RequestList rl;
  rl.shutdown = r.u8() != 0;
  uint32_t n = r.u32();
  for (uint32_t i = 0; r.ok() && i < n; i++) {
    Request req;
    if (!parse_request(r, &req)) return -1;
    rl.requests.push_back(std::move(req));
  }
  if (!r.done()) return -1;
  Writer w;
  w.u8(rl.shutdown ? 1 : 0);
  w.u32(uint32_t(rl.requests.size()));
  for (const auto& req : rl.requests) write_request(w, req);
  *out = w.release(out_len);
  return *out ? 0 : -2;
}

int hvd_wire_reencode_response_list(const uint8_t* in, int64_t len,
                                    uint8_t** out, int64_t* out_len) {
  Reader r(in, len);
  ResponseList rl;
  rl.shutdown = r.u8() != 0;
  rl.tuned_cycle_time_ms = r.f64();
  rl.tuned_fusion_threshold_bytes = r.i64();
  uint32_t n = r.u32();
  for (uint32_t i = 0; r.ok() && i < n; i++) {
    Response resp;
    if (!parse_response(r, &resp)) return -1;
    rl.responses.push_back(std::move(resp));
  }
  if (!r.done()) return -1;
  Writer w;
  w.u8(rl.shutdown ? 1 : 0);
  w.f64(rl.tuned_cycle_time_ms);
  w.i64(rl.tuned_fusion_threshold_bytes);
  w.u32(uint32_t(rl.responses.size()));
  for (const auto& resp : rl.responses) write_response(w, resp);
  *out = w.release(out_len);
  return *out ? 0 : -2;
}

}  // extern "C"
