// Native runtime core: batched frame transport, fusion pack/unpack,
// reduction kernels. See hvdtpu.h for the contract.
//
// Design notes (TPU-native re-architecture, not a translation):
// - The reference's per-cycle control plane is MPI_Gather/MPI_Bcast
//   (reference: horovod/common/operations.cc:1044-1065,1249-1251);
//   here it is a poll(2) loop over persistent TCP fds that services
//   all workers concurrently in one syscall-driven pass, called from
//   Python with the GIL released (ctypes releases it automatically).
// - HMAC-SHA256 framing matches horovod_tpu/common/network.py; SHA-256
//   is implemented inline (FIPS 180-4) and cross-checked against
//   hashlib in tests/test_native.py.

#include "hvdtpu.h"

#include <cerrno>
#include <cmath>
#include <cstdlib>
#include <cstring>
#include <ctime>
#include <poll.h>
#include <sys/socket.h>
#include <sys/uio.h>
#include <unistd.h>

// <linux/errqueue.h> needs struct timespec / sockaddr complete, so it
// must follow <ctime> and <sys/socket.h> (MSG_ZEROCOPY completions).
#include <linux/errqueue.h>

#ifdef HVD_HAVE_IO_URING
#include <linux/io_uring.h>
#include <sys/mman.h>
#include <sys/syscall.h>
#endif

#include <atomic>
#include <vector>

// ---------------------------------------------------------------------
// SHA-256 (FIPS 180-4) + HMAC
// ---------------------------------------------------------------------

namespace {

// Same clock Python's time.monotonic() reads on Linux, so the
// per-peer arrival stamps the gather loops export compare directly
// against Python-side stamps (straggler attribution, common/trace.py).
inline double now_mono() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return double(ts.tv_sec) + double(ts.tv_nsec) * 1e-9;
}

struct Sha256 {
  uint32_t h[8];
  uint64_t bits = 0;
  uint8_t buf[64];
  size_t buf_len = 0;

  Sha256() {
    static const uint32_t init[8] = {
        0x6a09e667u, 0xbb67ae85u, 0x3c6ef372u, 0xa54ff53au,
        0x510e527fu, 0x9b05688cu, 0x1f83d9abu, 0x5be0cd19u};
    memcpy(h, init, sizeof(h));
  }

  static uint32_t rotr(uint32_t x, int n) {
    return (x >> n) | (x << (32 - n));
  }

  void block(const uint8_t* p) {
    static const uint32_t k[64] = {
        0x428a2f98u, 0x71374491u, 0xb5c0fbcfu, 0xe9b5dba5u, 0x3956c25bu,
        0x59f111f1u, 0x923f82a4u, 0xab1c5ed5u, 0xd807aa98u, 0x12835b01u,
        0x243185beu, 0x550c7dc3u, 0x72be5d74u, 0x80deb1feu, 0x9bdc06a7u,
        0xc19bf174u, 0xe49b69c1u, 0xefbe4786u, 0x0fc19dc6u, 0x240ca1ccu,
        0x2de92c6fu, 0x4a7484aau, 0x5cb0a9dcu, 0x76f988dau, 0x983e5152u,
        0xa831c66du, 0xb00327c8u, 0xbf597fc7u, 0xc6e00bf3u, 0xd5a79147u,
        0x06ca6351u, 0x14292967u, 0x27b70a85u, 0x2e1b2138u, 0x4d2c6dfcu,
        0x53380d13u, 0x650a7354u, 0x766a0abbu, 0x81c2c92eu, 0x92722c85u,
        0xa2bfe8a1u, 0xa81a664bu, 0xc24b8b70u, 0xc76c51a3u, 0xd192e819u,
        0xd6990624u, 0xf40e3585u, 0x106aa070u, 0x19a4c116u, 0x1e376c08u,
        0x2748774cu, 0x34b0bcb5u, 0x391c0cb3u, 0x4ed8aa4au, 0x5b9cca4fu,
        0x682e6ff3u, 0x748f82eeu, 0x78a5636fu, 0x84c87814u, 0x8cc70208u,
        0x90befffau, 0xa4506cebu, 0xbef9a3f7u, 0xc67178f2u};
    uint32_t w[64];
    for (int i = 0; i < 16; i++) {
      w[i] = (uint32_t(p[4 * i]) << 24) | (uint32_t(p[4 * i + 1]) << 16) |
             (uint32_t(p[4 * i + 2]) << 8) | uint32_t(p[4 * i + 3]);
    }
    for (int i = 16; i < 64; i++) {
      uint32_t s0 = rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18) ^
                    (w[i - 15] >> 3);
      uint32_t s1 = rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19) ^
                    (w[i - 2] >> 10);
      w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    uint32_t a = h[0], b = h[1], c = h[2], d = h[3], e = h[4], f = h[5],
             g = h[6], hh = h[7];
    for (int i = 0; i < 64; i++) {
      uint32_t s1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
      uint32_t ch = (e & f) ^ (~e & g);
      uint32_t t1 = hh + s1 + ch + k[i] + w[i];
      uint32_t s0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
      uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
      uint32_t t2 = s0 + maj;
      hh = g; g = f; f = e; e = d + t1;
      d = c; c = b; b = a; a = t1 + t2;
    }
    h[0] += a; h[1] += b; h[2] += c; h[3] += d;
    h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
  }

  void update(const uint8_t* p, size_t n) {
    bits += uint64_t(n) * 8;
    if (buf_len) {
      size_t take = 64 - buf_len < n ? 64 - buf_len : n;
      memcpy(buf + buf_len, p, take);
      buf_len += take; p += take; n -= take;
      if (buf_len == 64) { block(buf); buf_len = 0; }
    }
    while (n >= 64) { block(p); p += 64; n -= 64; }
    if (n) { memcpy(buf, p, n); buf_len = n; }
  }

  void final(uint8_t out[32]) {
    uint8_t pad[72] = {0x80};
    size_t pad_len = (buf_len < 56) ? 56 - buf_len : 120 - buf_len;
    uint64_t bits_be = bits;
    uint8_t lenb[8];
    for (int i = 7; i >= 0; i--) { lenb[i] = bits_be & 0xff; bits_be >>= 8; }
    update(pad, pad_len);
    update(lenb, 8);
    for (int i = 0; i < 8; i++) {
      out[4 * i] = h[i] >> 24; out[4 * i + 1] = (h[i] >> 16) & 0xff;
      out[4 * i + 2] = (h[i] >> 8) & 0xff; out[4 * i + 3] = h[i] & 0xff;
    }
  }
};

// Incremental HMAC-SHA256 so scatter-gather payloads (vectored sends,
// segment-wise receives) can be authenticated without assembling one
// contiguous buffer first.
struct Hmac {
  Sha256 inner;
  uint8_t opad[64];

  Hmac(const uint8_t* key, size_t key_len) {
    uint8_t k[64] = {0};
    if (key_len > 64) {
      Sha256 kh; kh.update(key, key_len); kh.final(k);  // k[32..] zero
    } else if (key_len) {
      memcpy(k, key, key_len);
    }
    uint8_t ipad[64];
    for (int i = 0; i < 64; i++) {
      ipad[i] = k[i] ^ 0x36; opad[i] = k[i] ^ 0x5c;
    }
    inner.update(ipad, 64);
  }

  void update(const void* p, size_t n) {
    inner.update(static_cast<const uint8_t*>(p), n);
  }

  void final(uint8_t out[32]) {
    uint8_t ih[32];
    inner.final(ih);
    Sha256 ho;
    ho.update(opad, 64);
    ho.update(ih, 32);
    ho.final(out);
  }
};

void hmac_sha256(const uint8_t* key, size_t key_len, const uint8_t* tag1,
                 const uint8_t* msg, size_t msg_len, uint8_t out[32]) {
  Hmac h(key, key_len);
  if (tag1) h.update(tag1, 1);
  h.update(msg, msg_len);
  h.final(out);
}

// constant-time digest compare
bool digest_eq(const uint8_t a[32], const uint8_t b[32]) {
  uint8_t diff = 0;
  for (int i = 0; i < 32; i++) diff |= uint8_t(a[i] ^ b[i]);
  return diff == 0;
}

// ---------------------------------------------------------------------
// blocking-socket helpers
// ---------------------------------------------------------------------

int write_all(int fd, const uint8_t* p, size_t n) {
  while (n) {
    ssize_t w = ::send(fd, p, n, MSG_NOSIGNAL);
    if (w < 0) {
      if (errno == EINTR) continue;
      return -errno;
    }
    p += w; n -= size_t(w);
  }
  return 0;
}

int read_all(int fd, uint8_t* p, size_t n) {
  while (n) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r < 0) {
      if (errno == EINTR) continue;
      return -errno;
    }
    if (r == 0) return -ECONNRESET;
    p += r; n -= size_t(r);
  }
  return 0;
}

// Total-silence deadline shared by the zero-copy receive paths: the
// wait is sliced into interval_ms polls (on_idle fires per idle slice
// — the coordinator's PING fan-out), idle_ms accumulates across reads
// within ONE logical wait, and any received byte resets it — the same
// semantics as network.Channel.arm, so a big frame trickling in over
// a slow link never false-positives.
struct Deadline {
  int timeout_ms;   // < 0: wait forever
  int interval_ms;  // poll slice (clamped >= 1 when armed)
  void (*on_idle)();
  int idle_ms = 0;
};

int dl_read(int fd, uint8_t* p, size_t n, Deadline* dl) {
  while (n) {
    if (dl != nullptr && dl->timeout_ms >= 0) {
      struct pollfd pf;
      pf.fd = fd; pf.events = POLLIN; pf.revents = 0;
      int rc = ::poll(&pf, 1, dl->interval_ms);
      if (rc < 0) {
        if (errno == EINTR) continue;
        return -errno;
      }
      if (rc == 0) {
        if (dl->on_idle) dl->on_idle();
        dl->idle_ms += dl->interval_ms;
        if (dl->idle_ms >= dl->timeout_ms) return -ETIMEDOUT;
        continue;
      }
    }
    ssize_t r = ::recv(fd, p, n, 0);
    if (r < 0) {
      if (errno == EINTR) continue;
      if ((errno == EAGAIN || errno == EWOULDBLOCK) && dl != nullptr &&
          dl->timeout_ms >= 0) {
        // SO_RCVTIMEO (armed by Channel.arm on this fd) fired under
        // the poll's feet: count it as one idle slice.
        if (dl->on_idle) dl->on_idle();
        dl->idle_ms += dl->interval_ms;
        if (dl->idle_ms >= dl->timeout_ms) return -ETIMEDOUT;
        continue;
      }
      return -errno;
    }
    if (r == 0) return -ECONNRESET;
    p += r; n -= size_t(r);
    if (dl != nullptr) dl->idle_ms = 0;
  }
  return 0;
}

// Looped sendmsg over an iovec array, adjusting bases on partial
// writes; mutates iov in place.
int sendv_all(int fd, struct iovec* iov, int niov) {
  while (niov > 0) {
    struct msghdr msg;
    memset(&msg, 0, sizeof(msg));
    msg.msg_iov = iov;
    msg.msg_iovlen = size_t(niov);
    ssize_t w = ::sendmsg(fd, &msg, MSG_NOSIGNAL);
    if (w < 0) {
      if (errno == EINTR) continue;
      return -errno;
    }
    size_t left = size_t(w);
    while (niov > 0 && left >= iov->iov_len) {
      left -= iov->iov_len;
      iov++; niov--;
    }
    if (niov > 0 && left) {
      iov->iov_base = static_cast<char*>(iov->iov_base) + left;
      iov->iov_len -= left;
    }
  }
  return 0;
}

// Frame a scatter-gather payload: header + optional digest + parts.
int send_frame_iov(int fd, uint8_t tag, const void* const* bufs,
                   const int64_t* lens, int niov,
                   const uint8_t* secret, int secret_len) {
  int64_t total = 0;
  for (int i = 0; i < niov; i++) {
    if (lens[i] < 0) return -EINVAL;
    total += lens[i];
  }
  if (uint64_t(total) > 0xffffffffull) return -EMSGSIZE;
  uint8_t hdr[5];
  uint32_t n32 = uint32_t(total);
  memcpy(hdr, &n32, 4);  // little-endian hosts only (x86/arm64)
  hdr[4] = tag;
  uint8_t digest[32];
  std::vector<struct iovec> iov;
  iov.reserve(size_t(niov) + 2);
  iov.push_back({hdr, 5});
  if (secret_len > 0) {
    Hmac h(secret, size_t(secret_len));
    h.update(&tag, 1);
    for (int i = 0; i < niov; i++)
      if (lens[i]) h.update(bufs[i], size_t(lens[i]));
    h.final(digest);
    iov.push_back({digest, 32});
  }
  for (int i = 0; i < niov; i++)
    if (lens[i])
      iov.push_back({const_cast<void*>(bufs[i]), size_t(lens[i])});
  return sendv_all(fd, iov.data(), int(iov.size()));
}

bool tag_in(uint8_t tag, const uint8_t* tags, int n) {
  for (int i = 0; i < n; i++)
    if (tags[i] == tag) return true;
  return false;
}

// Drain + authenticate one frame body of length n into a malloc'd
// buffer (deviation/skip paths). *out receives the payload (caller
// frees) unless out == nullptr, in which case it is freed here.
// ``pre``/``pre_len`` is an already-read head of the payload to
// stitch back on (deviations detected after a partial read).
int drain_frame(int fd, uint32_t n, const uint8_t* pre, size_t pre_len,
                uint8_t tag, const uint8_t* secret, int secret_len,
                const uint8_t* digest, Deadline* dl, uint8_t** out) {
  uint8_t* buf = static_cast<uint8_t*>(malloc(n ? n : 1));
  if (!buf) return -ENOMEM;
  if (pre_len) memcpy(buf, pre, pre_len);
  int rc = dl_read(fd, buf + pre_len, n - pre_len, dl);
  if (rc) { free(buf); return rc; }
  if (secret_len > 0) {
    uint8_t expect[32];
    hmac_sha256(secret, size_t(secret_len), &tag, buf, n, expect);
    if (!digest_eq(digest, expect)) { free(buf); return -EBADMSG; }
  }
  if (out) *out = buf; else free(buf);
  return 0;
}

int send_frame(int fd, uint8_t tag, const uint8_t* payload, int64_t len,
               const uint8_t* secret, int secret_len) {
  if (len < 0 || uint64_t(len) > 0xffffffffull) return -EMSGSIZE;
  uint8_t hdr[5];
  uint32_t n32 = uint32_t(len);
  memcpy(hdr, &n32, 4);  // little-endian hosts only (x86/arm64)
  hdr[4] = tag;
  int rc = write_all(fd, hdr, 5);
  if (rc) return rc;
  if (secret_len > 0) {
    uint8_t digest[32];
    hmac_sha256(secret, size_t(secret_len), &tag, payload, size_t(len),
                digest);
    rc = write_all(fd, digest, 32);
    if (rc) return rc;
  }
  return write_all(fd, payload, size_t(len));
}

int recv_frame(int fd, const uint8_t* secret, int secret_len,
               uint8_t** out, int64_t* out_len, uint8_t* out_tag) {
  uint8_t hdr[5];
  int rc = read_all(fd, hdr, 5);
  if (rc) return rc;
  uint32_t n32;
  memcpy(&n32, hdr, 4);
  uint8_t tag = hdr[4];
  uint8_t digest[32];
  if (secret_len > 0) {
    rc = read_all(fd, digest, 32);
    if (rc) return rc;
  }
  uint8_t* buf = static_cast<uint8_t*>(malloc(n32 ? n32 : 1));
  if (!buf) return -ENOMEM;
  rc = read_all(fd, buf, n32);
  if (rc) { free(buf); return rc; }
  if (secret_len > 0) {
    uint8_t expect[32];
    hmac_sha256(secret, size_t(secret_len), &tag, buf, n32, expect);
    // constant-time compare
    uint8_t diff = 0;
    for (int i = 0; i < 32; i++) diff |= uint8_t(digest[i] ^ expect[i]);
    if (diff) { free(buf); return -EBADMSG; }
  }
  *out = buf;
  *out_len = n32;
  *out_tag = tag;
  return 0;
}

// Outcomes of recv_expected (non-negative; errors stay negative).
enum { RX_MATCH = 0, RX_DEV = 1, RX_SKIP = 2 };

// Receive one frame that SHOULD be the steady-cycle layout
// (want_tag, prefix, per-segment headers, segment data into
// data_ptrs). Anything else is drained whole and either discarded
// (skip_tags) or handed back as a deviation for the Python classic
// path. Authentication covers every byte exactly as Channel framing
// does, including deviations.
int recv_expected(int fd, uint8_t want_tag,
                  const uint8_t* prefix, int64_t prefix_len,
                  const uint8_t* const* seg_hdrs,
                  const int64_t* seg_hdr_lens,
                  void* const* data_ptrs, const int64_t* seg_lens,
                  int nseg, const uint8_t* secret, int secret_len,
                  const uint8_t* skip_tags, int nskip, Deadline* dl,
                  uint8_t** dev_buf, int64_t* dev_len,
                  uint8_t* dev_tag) {
  int64_t expected = prefix_len;
  for (int i = 0; i < nseg; i++)
    expected += seg_hdr_lens[i] + seg_lens[i];
  uint8_t hdr[5];
  int rc = dl_read(fd, hdr, 5, dl);
  if (rc) return rc;
  uint32_t n32;
  memcpy(&n32, hdr, 4);
  uint8_t tag = hdr[4];
  uint8_t digest[32];
  if (secret_len > 0) {
    rc = dl_read(fd, digest, 32, dl);
    if (rc) return rc;
  }
  if (tag_in(tag, skip_tags, nskip)) {
    rc = drain_frame(fd, n32, nullptr, 0, tag, secret, secret_len,
                     digest, dl, nullptr);
    return rc ? rc : RX_SKIP;
  }
  if (tag != want_tag || int64_t(n32) != expected) {
    rc = drain_frame(fd, n32, nullptr, 0, tag, secret, secret_len,
                     digest, dl, dev_buf);
    if (rc) return rc;
    *dev_len = n32;
    *dev_tag = tag;
    return RX_DEV;
  }
  std::vector<uint8_t> scratch(static_cast<size_t>(prefix_len));
  rc = dl_read(fd, scratch.data(), size_t(prefix_len), dl);
  if (rc) return rc;
  Hmac h(secret, size_t(secret_len > 0 ? secret_len : 0));
  if (secret_len > 0) {
    h.update(&tag, 1);
    h.update(scratch.data(), size_t(prefix_len));
  }
  if (memcmp(scratch.data(), prefix, size_t(prefix_len)) != 0) {
    rc = drain_frame(fd, n32, scratch.data(), size_t(prefix_len), tag,
                     secret, secret_len, digest, dl, dev_buf);
    if (rc) return rc;
    *dev_len = n32;
    *dev_tag = tag;
    return RX_DEV;
  }
  std::vector<uint8_t> hscratch;
  for (int i = 0; i < nseg; i++) {
    hscratch.resize(size_t(seg_hdr_lens[i]));
    rc = dl_read(fd, hscratch.data(), size_t(seg_hdr_lens[i]), dl);
    if (rc) return rc;
    if (memcmp(hscratch.data(), seg_hdrs[i],
               size_t(seg_hdr_lens[i])) != 0) {
      // Reassemble everything already consumed (prefix + earlier
      // segments + this header), then drain the rest — a rare
      // transition cycle pays a copy; steady cycles never land here.
      uint8_t* buf = static_cast<uint8_t*>(malloc(n32 ? n32 : 1));
      if (!buf) return -ENOMEM;
      size_t off = 0;
      memcpy(buf, prefix, size_t(prefix_len));
      off += size_t(prefix_len);
      for (int k = 0; k < i; k++) {
        memcpy(buf + off, seg_hdrs[k], size_t(seg_hdr_lens[k]));
        off += size_t(seg_hdr_lens[k]);
        memcpy(buf + off, data_ptrs[k], size_t(seg_lens[k]));
        off += size_t(seg_lens[k]);
      }
      memcpy(buf + off, hscratch.data(), size_t(seg_hdr_lens[i]));
      off += size_t(seg_hdr_lens[i]);
      rc = dl_read(fd, buf + off, size_t(n32) - off, dl);
      if (rc) { free(buf); return rc; }
      if (secret_len > 0) {
        uint8_t expect[32];
        hmac_sha256(secret, size_t(secret_len), &tag, buf, n32,
                    expect);
        if (!digest_eq(digest, expect)) { free(buf); return -EBADMSG; }
      }
      *dev_buf = buf;
      *dev_len = n32;
      *dev_tag = tag;
      return RX_DEV;
    }
    if (secret_len > 0)
      h.update(hscratch.data(), size_t(seg_hdr_lens[i]));
    rc = dl_read(fd, static_cast<uint8_t*>(data_ptrs[i]),
                 size_t(seg_lens[i]), dl);
    if (rc) return rc;
    if (secret_len > 0) h.update(data_ptrs[i], size_t(seg_lens[i]));
  }
  if (secret_len > 0) {
    uint8_t expect[32];
    h.final(expect);
    if (!digest_eq(digest, expect)) return -EBADMSG;
  }
  return RX_MATCH;
}

// dtype code -> element size (codes as for hvd_sum_into).
const int kDtypeSize[] = {4, 8, 4, 8, 1, 2, 2};

// Scalar fp16/bf16 <-> f32 conversions shared by the reduction kernel
// (hvd_sum_into) and the wire-compression cast (hvd_cast). fp16 via
// f32 round-trip (reference: common/half.cc:42-77, scalar path — no
// F16C dependence); bf16 is the upper 16 bits of an f32 with
// round-to-nearest-even on the way down.
inline float half_to_float(uint16_t v) {
  uint32_t sign = uint32_t(v & 0x8000u) << 16;
  uint32_t exp = (v >> 10) & 0x1f;
  uint32_t man = v & 0x3ffu;
  uint32_t f;
  if (exp == 0) {
    if (man == 0) {
      f = sign;
    } else {
      exp = 127 - 15 + 1;
      while (!(man & 0x400u)) { man <<= 1; exp--; }
      man &= 0x3ffu;
      f = sign | (exp << 23) | (man << 13);
    }
  } else if (exp == 31) {
    f = sign | 0x7f800000u | (man << 13);
  } else {
    f = sign | ((exp - 15 + 127) << 23) | (man << 13);
  }
  float out;
  memcpy(&out, &f, 4);
  return out;
}

inline uint16_t float_to_half(float x) {
  uint32_t f;
  memcpy(&f, &x, 4);
  uint32_t sign = (f >> 16) & 0x8000u;
  int32_t exp = int32_t((f >> 23) & 0xff) - 127 + 15;
  uint32_t man = f & 0x7fffffu;
  if (((f >> 23) & 0xff) == 0xff && man != 0)
    return uint16_t(sign | 0x7e00u);  // NaN stays NaN, not Inf
  if (exp <= 0) {
    if (exp < -10) return uint16_t(sign);
    man |= 0x800000u;
    uint32_t shift = uint32_t(14 - exp);
    uint32_t half_man = man >> shift;
    // round to nearest even
    uint32_t rem = man & ((1u << shift) - 1);
    uint32_t halfway = 1u << (shift - 1);
    if (rem > halfway || (rem == halfway && (half_man & 1)))
      half_man++;
    return uint16_t(sign | half_man);
  }
  if (exp >= 31) return uint16_t(sign | 0x7c00u);
  uint32_t half = sign | (uint32_t(exp) << 10) | (man >> 13);
  uint32_t rem = man & 0x1fffu;
  if (rem > 0x1000u || (rem == 0x1000u && (half & 1))) half++;
  return uint16_t(half);
}

inline float bf16_to_float(uint16_t v) {
  uint32_t f = uint32_t(v) << 16;
  float out;
  memcpy(&out, &f, 4);
  return out;
}

inline uint16_t float_to_bf16(float x) {
  uint32_t f;
  memcpy(&f, &x, 4);
  if ((f & 0x7fffffffu) > 0x7f800000u)
    return uint16_t((f >> 16) | 0x0040u);  // quiet NaN
  uint32_t rounding = 0x7fffu + ((f >> 16) & 1u);
  return uint16_t((f + rounding) >> 16);
}

}  // namespace

// ---------------------------------------------------------------------
// C API
// ---------------------------------------------------------------------

extern "C" {

int hvd_gather_frames(const int* fds, int n, const uint8_t* secret,
                      int secret_len, uint8_t** bufs, int64_t* lens,
                      uint8_t* tags, int timeout_ms, double* arrive) {
  // Poll-driven: service whichever worker's frame arrives first so one
  // slow rank doesn't serialize the reads (the reference gets this
  // from MPI_Gatherv internally).
  std::vector<bool> done(size_t(n), false);
  int remaining = n;
  std::vector<struct pollfd> pfds(static_cast<size_t>(n));
  while (remaining > 0) {
    int active = 0;
    for (int i = 0; i < n; i++) {
      if (!done[size_t(i)]) {
        pfds[size_t(active)].fd = fds[i];
        pfds[size_t(active)].events = POLLIN;
        pfds[size_t(active)].revents = 0;
        active++;
      }
    }
    int rc = ::poll(pfds.data(), nfds_t(active), timeout_ms);
    if (rc < 0) {
      if (errno == EINTR) continue;
      return -errno;
    }
    if (rc == 0) return -ETIMEDOUT;
    for (int j = 0; j < active; j++) {
      if (!(pfds[size_t(j)].revents & (POLLIN | POLLHUP | POLLERR)))
        continue;
      // Map fd back to index (n is small: one entry per worker).
      int idx = -1;
      for (int i = 0; i < n; i++) {
        if (!done[size_t(i)] && fds[i] == pfds[size_t(j)].fd) {
          idx = i;
          break;
        }
      }
      if (idx < 0) continue;
      int rrc = recv_frame(fds[idx], secret, secret_len, &bufs[idx],
                           &lens[idx], &tags[idx]);
      if (rrc) return rrc;
      if (arrive) arrive[idx] = now_mono();
      done[size_t(idx)] = true;
      remaining--;
    }
  }
  return 0;
}

int hvd_broadcast_frame(const int* fds, int n, uint8_t tag,
                        const uint8_t* payload, int64_t len,
                        const uint8_t* secret, int secret_len) {
  for (int i = 0; i < n; i++) {
    int rc = send_frame(fds[i], tag, payload, len, secret, secret_len);
    if (rc) return rc;
  }
  return 0;
}

int hvd_scatter_frames(const int* fds, int n, uint8_t tag,
                       const uint8_t* const* payloads,
                       const int64_t* lens, const uint8_t* secret,
                       int secret_len) {
  for (int i = 0; i < n; i++) {
    int rc = send_frame(fds[i], tag, payloads[i], lens[i], secret,
                        secret_len);
    if (rc) return rc;
  }
  return 0;
}

void hvd_free(uint8_t* buf) { free(buf); }

void hvd_pack(const void* const* srcs, const int64_t* nbytes, int n,
              void* dst) {
  uint8_t* out = static_cast<uint8_t*>(dst);
  for (int i = 0; i < n; i++) {
    memcpy(out, srcs[i], size_t(nbytes[i]));
    out += nbytes[i];
  }
}

void hvd_unpack(const void* src, const int64_t* nbytes, int n,
                void* const* dsts) {
  const uint8_t* in = static_cast<const uint8_t*>(src);
  for (int i = 0; i < n; i++) {
    memcpy(const_cast<void*>(dsts[i]), in, size_t(nbytes[i]));
    in += nbytes[i];
  }
}

int hvd_sum_into(void* acc, const void* src, int64_t count, int dtype) {
  switch (dtype) {
    case 0: {
      float* a = static_cast<float*>(acc);
      const float* s = static_cast<const float*>(src);
      for (int64_t i = 0; i < count; i++) a[i] += s[i];
      return 0;
    }
    case 1: {
      double* a = static_cast<double*>(acc);
      const double* s = static_cast<const double*>(src);
      for (int64_t i = 0; i < count; i++) a[i] += s[i];
      return 0;
    }
    case 2: {
      int32_t* a = static_cast<int32_t*>(acc);
      const int32_t* s = static_cast<const int32_t*>(src);
      for (int64_t i = 0; i < count; i++) a[i] += s[i];
      return 0;
    }
    case 3: {
      int64_t* a = static_cast<int64_t*>(acc);
      const int64_t* s = static_cast<const int64_t*>(src);
      for (int64_t i = 0; i < count; i++) a[i] += s[i];
      return 0;
    }
    case 4: {
      uint8_t* a = static_cast<uint8_t*>(acc);
      const uint8_t* s = static_cast<const uint8_t*>(src);
      for (int64_t i = 0; i < count; i++) a[i] = uint8_t(a[i] + s[i]);
      return 0;
    }
    case 5: {
      // fp16 accumulated via the shared f32 round-trip helpers.
      uint16_t* a = static_cast<uint16_t*>(acc);
      const uint16_t* s = static_cast<const uint16_t*>(src);
      for (int64_t i = 0; i < count; i++)
        a[i] = float_to_half(half_to_float(a[i]) + half_to_float(s[i]));
      return 0;
    }
    case 6: {
      // bfloat16 — the TPU-native wire/accumulate dtype: accumulate
      // in f32, round to nearest-even on the way back (role-parity
      // with the fp16 sum above; reference analog: common/half.cc).
      uint16_t* a = static_cast<uint16_t*>(acc);
      const uint16_t* s = static_cast<const uint16_t*>(src);
      for (int64_t i = 0; i < count; i++)
        a[i] = float_to_bf16(bf16_to_float(a[i]) + bf16_to_float(s[i]));
      return 0;
    }
    default:
      return -EINVAL;
  }
}

int hvd_cast(const void* src, void* dst, int64_t count, int src_dtype,
             int dst_dtype) {
  // The wire-compression cast leg: f32 <-> bf16/f16, the pairs the
  // negotiated wire dtypes need on the zero-copy steady path (pack
  // compresses straight into the fusion arena; decompress lands in a
  // fresh output buffer). Unsupported pairs return -EINVAL and the
  // caller falls back to numpy's casting machinery.
  if (src_dtype == 0 && dst_dtype == 6) {
    const float* s = static_cast<const float*>(src);
    uint16_t* d = static_cast<uint16_t*>(dst);
    for (int64_t i = 0; i < count; i++) d[i] = float_to_bf16(s[i]);
    return 0;
  }
  if (src_dtype == 6 && dst_dtype == 0) {
    const uint16_t* s = static_cast<const uint16_t*>(src);
    float* d = static_cast<float*>(dst);
    for (int64_t i = 0; i < count; i++) d[i] = bf16_to_float(s[i]);
    return 0;
  }
  if (src_dtype == 0 && dst_dtype == 5) {
    const float* s = static_cast<const float*>(src);
    uint16_t* d = static_cast<uint16_t*>(dst);
    for (int64_t i = 0; i < count; i++) d[i] = float_to_half(s[i]);
    return 0;
  }
  if (src_dtype == 5 && dst_dtype == 0) {
    const uint16_t* s = static_cast<const uint16_t*>(src);
    float* d = static_cast<float*>(dst);
    for (int64_t i = 0; i < count; i++) d[i] = half_to_float(s[i]);
    return 0;
  }
  return -EINVAL;
}

void hvd_hmac_sha256(const uint8_t* key, int key_len, uint8_t tag,
                     const uint8_t* payload, int64_t len, uint8_t* out) {
  hmac_sha256(key, size_t(key_len), &tag, payload, size_t(len), out);
}

int hvd_sendv(int fd, uint8_t tag, const void* const* bufs,
              const int64_t* lens, int niov,
              const uint8_t* secret, int secret_len) {
  return send_frame_iov(fd, tag, bufs, lens, niov, secret, secret_len);
}

int hvd_recv_into(int fd, const uint8_t* secret, int secret_len,
                  void* buf, int64_t cap,
                  const uint8_t* skip_tags, int nskip,
                  int64_t* out_len, uint8_t* out_tag,
                  int timeout_ms, int interval_ms,
                  uint8_t** spill) {
  Deadline dl{timeout_ms, interval_ms > 0 ? interval_ms : 1, nullptr};
  while (true) {
    uint8_t hdr[5];
    int rc = dl_read(fd, hdr, 5, &dl);
    if (rc) return rc;
    uint32_t n32;
    memcpy(&n32, hdr, 4);
    uint8_t tag = hdr[4];
    uint8_t digest[32];
    if (secret_len > 0) {
      rc = dl_read(fd, digest, 32, &dl);
      if (rc) return rc;
    }
    if (tag_in(tag, skip_tags, nskip)) {
      rc = drain_frame(fd, n32, nullptr, 0, tag, secret, secret_len,
                       digest, &dl, nullptr);
      if (rc) return rc;
      continue;
    }
    *out_tag = tag;
    *out_len = n32;
    if (int64_t(n32) > cap) {
      rc = drain_frame(fd, n32, nullptr, 0, tag, secret, secret_len,
                       digest, &dl, spill);
      return rc ? rc : 1;
    }
    rc = dl_read(fd, static_cast<uint8_t*>(buf), n32, &dl);
    if (rc) return rc;
    if (secret_len > 0) {
      uint8_t expect[32];
      hmac_sha256(secret, size_t(secret_len), &tag,
                  static_cast<const uint8_t*>(buf), n32, expect);
      if (!digest_eq(digest, expect)) return -EBADMSG;
    }
    return 0;
  }
}

int hvd_steady_worker(int fd, uint8_t req_tag, uint8_t resp_tag,
                      const uint8_t* prefix, int64_t prefix_len,
                      const uint8_t* const* seg_hdrs,
                      const int64_t* seg_hdr_lens,
                      const void* const* send_ptrs,
                      void* const* recv_ptrs,
                      const int64_t* seg_lens, int nseg,
                      const uint8_t* secret, int secret_len,
                      const uint8_t* skip_tags, int nskip,
                      int timeout_ms, int interval_ms,
                      uint8_t** dev_buf, int64_t* dev_len,
                      uint8_t* dev_tag) {
  // 1. the speculative request frame, straight from the fusion arena
  std::vector<const void*> bufs;
  std::vector<int64_t> lens;
  bufs.reserve(size_t(2 * nseg) + 1);
  lens.reserve(size_t(2 * nseg) + 1);
  bufs.push_back(prefix);
  lens.push_back(prefix_len);
  for (int i = 0; i < nseg; i++) {
    bufs.push_back(seg_hdrs[i]);
    lens.push_back(seg_hdr_lens[i]);
    bufs.push_back(send_ptrs[i]);
    lens.push_back(seg_lens[i]);
  }
  int rc = send_frame_iov(fd, req_tag, bufs.data(), lens.data(),
                          int(bufs.size()), secret, secret_len);
  if (rc) return rc;
  // 2. the world-reduced response, straight into the result buffers
  Deadline dl{timeout_ms, interval_ms > 0 ? interval_ms : 1, nullptr};
  while (true) {
    rc = recv_expected(fd, resp_tag, prefix, prefix_len, seg_hdrs,
                       seg_hdr_lens, recv_ptrs, seg_lens, nseg,
                       secret, secret_len, skip_tags, nskip, &dl,
                       dev_buf, dev_len, dev_tag);
    if (rc == RX_SKIP) continue;
    return rc;  // RX_MATCH (0), RX_DEV (1) or negative errno
  }
}

// dtype-code itemsize (codes as hvd_sum_into/hvd_cast).
static int64_t code_itemsize(int code) {
  switch (code) {
    case 0: return 4;   // f32
    case 1: return 8;   // f64
    case 2: return 4;   // i32
    case 3: return 8;   // i64
    case 4: return 1;   // u8
    case 5: return 2;   // f16
    case 6: return 2;   // bf16
    default: return 0;
  }
}

int hvd_steady_worker_chunked(int fd, uint8_t req_tag, uint8_t resp_tag,
                              const uint8_t* prefix, int64_t prefix_len,
                              const uint8_t* const* seg_hdrs,
                              const int64_t* seg_hdr_lens,
                              const void* const* send_ptrs,
                              const void* const* stage_ptrs,
                              const int* stage_codes,
                              int64_t chunk_bytes,
                              void* const* recv_ptrs,
                              const int64_t* seg_lens,
                              const int* wire_codes, int nseg,
                              const uint8_t* secret, int secret_len,
                              const uint8_t* skip_tags, int nskip,
                              int timeout_ms, int interval_ms,
                              uint8_t** dev_buf, int64_t* dev_len,
                              uint8_t* dev_tag) {
  if (chunk_bytes <= 0) chunk_bytes = 1 << 20;
  int64_t total = prefix_len;
  for (int j = 0; j < nseg; j++) total += seg_hdr_lens[j] + seg_lens[j];
  if (uint64_t(total) > 0xffffffffull) return -EMSGSIZE;
  uint8_t hdr[5];
  uint32_t n32 = uint32_t(total);
  memcpy(hdr, &n32, 4);  // little-endian hosts only (x86/arm64)
  hdr[4] = req_tag;
  int rc;
  if (secret_len > 0) {
    // The digest precedes the payload on the wire, so a cast-during-
    // send cannot start until the HMAC over the CAST bytes is known:
    // fuse the cast and HMAC into ONE cache-warm pass per chunk, then
    // ship the whole frame with a single vectored send.
    Hmac h(secret, size_t(secret_len));
    h.update(&req_tag, 1);
    if (prefix_len) h.update(prefix, size_t(prefix_len));
    for (int j = 0; j < nseg; j++) {
      if (seg_hdr_lens[j]) h.update(seg_hdrs[j], size_t(seg_hdr_lens[j]));
      if (!seg_lens[j]) continue;
      if (stage_ptrs[j] == nullptr || stage_codes[j] < 0) {
        h.update(send_ptrs[j], size_t(seg_lens[j]));
        continue;
      }
      int64_t wisz = code_itemsize(wire_codes[j]);
      int64_t sisz = code_itemsize(stage_codes[j]);
      if (!wisz || !sisz) return -EINVAL;
      int64_t count = seg_lens[j] / wisz;
      int64_t step = chunk_bytes / wisz;
      if (step < 1) step = 1;
      for (int64_t done = 0; done < count; done += step) {
        int64_t c = count - done < step ? count - done : step;
        rc = hvd_cast(
            static_cast<const char*>(stage_ptrs[j]) + done * sisz,
            const_cast<char*>(
                static_cast<const char*>(send_ptrs[j])) + done * wisz,
            c, stage_codes[j], wire_codes[j]);
        if (rc) return rc;
        h.update(static_cast<const char*>(send_ptrs[j]) + done * wisz,
                 size_t(c * wisz));
      }
    }
    uint8_t digest[32];
    h.final(digest);
    std::vector<struct iovec> iov;
    iov.reserve(size_t(2 * nseg) + 3);
    iov.push_back({hdr, 5});
    iov.push_back({digest, 32});
    if (prefix_len)
      iov.push_back({const_cast<uint8_t*>(prefix), size_t(prefix_len)});
    for (int j = 0; j < nseg; j++) {
      if (seg_hdr_lens[j])
        iov.push_back({const_cast<uint8_t*>(seg_hdrs[j]),
                       size_t(seg_hdr_lens[j])});
      if (seg_lens[j])
        iov.push_back({const_cast<void*>(send_ptrs[j]),
                       size_t(seg_lens[j])});
    }
    rc = sendv_all(fd, iov.data(), int(iov.size()));
    if (rc) return rc;
  } else {
    // No frame auth: true pipelining — cast chunk i+1 while the
    // kernel transmits chunk i (sendmsg returns once the bytes are
    // socket-buffered; the NIC drains asynchronously).
    rc = write_all(fd, hdr, 5);
    if (rc) return rc;
    if (prefix_len) {
      rc = write_all(fd, prefix, size_t(prefix_len));
      if (rc) return rc;
    }
    for (int j = 0; j < nseg; j++) {
      if (seg_hdr_lens[j]) {
        rc = write_all(fd, seg_hdrs[j], size_t(seg_hdr_lens[j]));
        if (rc) return rc;
      }
      if (!seg_lens[j]) continue;
      if (stage_ptrs[j] == nullptr || stage_codes[j] < 0) {
        rc = write_all(fd, static_cast<const uint8_t*>(send_ptrs[j]),
                       size_t(seg_lens[j]));
        if (rc) return rc;
        continue;
      }
      int64_t wisz = code_itemsize(wire_codes[j]);
      int64_t sisz = code_itemsize(stage_codes[j]);
      if (!wisz || !sisz) return -EINVAL;
      int64_t count = seg_lens[j] / wisz;
      int64_t step = chunk_bytes / wisz;
      if (step < 1) step = 1;
      for (int64_t done = 0; done < count; done += step) {
        int64_t c = count - done < step ? count - done : step;
        char* dst = const_cast<char*>(
            static_cast<const char*>(send_ptrs[j])) + done * wisz;
        rc = hvd_cast(
            static_cast<const char*>(stage_ptrs[j]) + done * sisz,
            dst, c, stage_codes[j], wire_codes[j]);
        if (rc) return rc;
        rc = write_all(fd, reinterpret_cast<const uint8_t*>(dst),
                       size_t(c * wisz));
        if (rc) return rc;
      }
    }
  }
  // Receive half: identical to hvd_steady_worker.
  Deadline dl{timeout_ms, interval_ms > 0 ? interval_ms : 1, nullptr};
  while (true) {
    rc = recv_expected(fd, resp_tag, prefix, prefix_len, seg_hdrs,
                       seg_hdr_lens, recv_ptrs, seg_lens, nseg,
                       secret, secret_len, skip_tags, nskip, &dl,
                       dev_buf, dev_len, dev_tag);
    if (rc == RX_SKIP) continue;
    return rc;  // RX_MATCH (0), RX_DEV (1) or negative errno
  }
}

int hvd_steady_coord(const int* fds, int n, uint8_t req_tag,
                     uint8_t resp_tag,
                     const uint8_t* prefix, int64_t prefix_len,
                     const uint8_t* const* seg_hdrs,
                     const int64_t* seg_hdr_lens,
                     const int64_t* seg_lens, const int* seg_dtypes,
                     int nseg,
                     uint8_t* const* peer_seg_ptrs,
                     void* const* acc_ptrs,
                     const uint8_t* secret, int secret_len,
                     const uint8_t* skip_tags, int nskip,
                     int timeout_ms, int interval_ms,
                     void (*on_idle)(void),
                     uint8_t* done, double* arrive,
                     int* dev_idx, uint8_t** dev_buf,
                     int64_t* dev_len, uint8_t* dev_tag) {
  // --- gather: one speculative frame per pending peer -----------------
  Deadline dl{timeout_ms, interval_ms > 0 ? interval_ms : 1, on_idle};
  std::vector<struct pollfd> pfds(static_cast<size_t>(n));
  int remaining = 0;
  for (int i = 0; i < n; i++)
    if (!done[i]) remaining++;
  while (remaining > 0) {
    int active = 0;
    for (int i = 0; i < n; i++) {
      if (!done[i]) {
        pfds[size_t(active)].fd = fds[i];
        pfds[size_t(active)].events = POLLIN;
        pfds[size_t(active)].revents = 0;
        active++;
      }
    }
    int rc = ::poll(pfds.data(), nfds_t(active),
                    dl.timeout_ms >= 0 ? dl.interval_ms : -1);
    if (rc < 0) {
      if (errno == EINTR) continue;
      return -errno;
    }
    if (rc == 0) {
      if (dl.on_idle) dl.on_idle();
      dl.idle_ms += dl.interval_ms;
      if (dl.idle_ms >= dl.timeout_ms) return -ETIMEDOUT;
      continue;
    }
    for (int j = 0; j < active && remaining > 0; j++) {
      if (!(pfds[size_t(j)].revents & (POLLIN | POLLHUP | POLLERR)))
        continue;
      int idx = -1;
      for (int i = 0; i < n; i++) {
        if (!done[i] && fds[i] == pfds[size_t(j)].fd) { idx = i; break; }
      }
      if (idx < 0) continue;
      std::vector<void*> data(static_cast<size_t>(nseg));
      for (int s = 0; s < nseg; s++)
        data[size_t(s)] = peer_seg_ptrs[idx * nseg + s];
      rc = recv_expected(fds[idx], req_tag, prefix, prefix_len,
                         seg_hdrs, seg_hdr_lens, data.data(), seg_lens,
                         nseg, secret, secret_len, skip_tags, nskip,
                         &dl, dev_buf, dev_len, dev_tag);
      if (rc == RX_SKIP) continue;  // liveness/stray: peer stays owed
      if (rc == RX_DEV) { *dev_idx = idx; return 1; }
      if (rc < 0) return rc;
      if (arrive) arrive[idx] = now_mono();
      done[idx] = 1;
      remaining--;
      dl.idle_ms = 0;
    }
  }
  // --- reduce: acc[s] += every peer's segment s -----------------------
  for (int s = 0; s < nseg; s++) {
    int code = seg_dtypes[s];
    if (code < 0 || size_t(code) >= sizeof(kDtypeSize) / sizeof(int))
      return -EINVAL;
    int64_t count = seg_lens[s] / kDtypeSize[code];
    for (int i = 0; i < n; i++) {
      int rc = hvd_sum_into(acc_ptrs[s], peer_seg_ptrs[i * nseg + s],
                            count, code);
      if (rc) return rc;
    }
  }
  // --- broadcast the reduced response (digest computed ONCE) ----------
  int64_t total = prefix_len;
  for (int s = 0; s < nseg; s++) total += seg_hdr_lens[s] + seg_lens[s];
  if (uint64_t(total) > 0xffffffffull) return -EMSGSIZE;
  uint8_t hdr[5];
  uint32_t n32 = uint32_t(total);
  memcpy(hdr, &n32, 4);
  hdr[4] = resp_tag;
  uint8_t digest[32];
  if (secret_len > 0) {
    Hmac h(secret, size_t(secret_len));
    h.update(&resp_tag, 1);
    h.update(prefix, size_t(prefix_len));
    for (int s = 0; s < nseg; s++) {
      h.update(seg_hdrs[s], size_t(seg_hdr_lens[s]));
      h.update(acc_ptrs[s], size_t(seg_lens[s]));
    }
    h.final(digest);
  }
  std::vector<struct iovec> proto;
  proto.reserve(size_t(2 * nseg) + 3);
  proto.push_back({hdr, 5});
  if (secret_len > 0) proto.push_back({digest, 32});
  proto.push_back({const_cast<uint8_t*>(prefix), size_t(prefix_len)});
  for (int s = 0; s < nseg; s++) {
    proto.push_back({const_cast<uint8_t*>(seg_hdrs[s]),
                     size_t(seg_hdr_lens[s])});
    proto.push_back({acc_ptrs[s], size_t(seg_lens[s])});
  }
  std::vector<struct iovec> iov(proto.size());
  for (int i = 0; i < n; i++) {
    iov = proto;  // sendv_all mutates its iovecs on partial writes
    int rc = sendv_all(fds[i], iov.data(), int(iov.size()));
    if (rc) return rc;
  }
  return 0;
}

}  // extern "C"

// ---------------------------------------------------------------------
// Batched-submission reactor + MSG_ZEROCOPY sends + int8 codec + relay
// ---------------------------------------------------------------------

// Older toolchain headers may predate these; the kernel ABI values are
// stable, so define the fallbacks and let the runtime decide.
#ifndef SO_ZEROCOPY
#define SO_ZEROCOPY 60
#endif
#ifndef MSG_ZEROCOPY
#define MSG_ZEROCOPY 0x4000000
#endif
#ifndef SO_EE_ORIGIN_ZEROCOPY
#define SO_EE_ORIGIN_ZEROCOPY 5
#endif
#ifndef SO_EE_CODE_ZEROCOPY_COPIED
#define SO_EE_CODE_ZEROCOPY_COPIED 1
#endif

namespace {

#ifdef HVD_HAVE_IO_URING

// Minimal raw-syscall io_uring wrapper (no liburing in the image).
// The ring is CACHED per thread (see gather_ring() below): setup is
// io_uring_setup + three MAP_POPULATE mmaps — hundreds of
// microseconds, which a per-call ring would charge to EVERY steady
// cycle, more than the batching saves on small worlds. Reuse means a
// returning call may leave one-shot POLL_ADDs (and an interval timer)
// pending; rather than tearing the ring down to cancel them, every
// call stamps its submissions with a generation counter in the high
// user_data bits and later calls drop stale completions on sight — a
// stale POLL_ADD only ever reported readiness, it never consumed
// bytes, so dropping it is free. The ring carries READINESS only
// (IORING_OP_POLL_ADD): the bytes are then read by the same frame
// loop the poll(2) backend uses, so both backends are byte-identical
// on the wire by construction.
struct UringReactor {
  int ring_fd = -1;
  unsigned sq_entries = 0;
  unsigned* sq_head = nullptr;
  unsigned* sq_tail = nullptr;
  unsigned* sq_mask = nullptr;
  unsigned* sq_array = nullptr;
  io_uring_sqe* sqes = nullptr;
  unsigned* cq_head = nullptr;
  unsigned* cq_tail = nullptr;
  unsigned* cq_mask = nullptr;
  io_uring_cqe* cqes = nullptr;
  void* sq_ptr = nullptr;
  size_t sq_len = 0;
  void* cq_ptr = nullptr;  // null when IORING_FEAT_SINGLE_MMAP
  size_t cq_len = 0;
  void* sqe_ptr = nullptr;
  size_t sqe_len = 0;

  ~UringReactor() { shutdown(); }

  void shutdown() {
    if (sqe_ptr) munmap(sqe_ptr, sqe_len);
    if (cq_ptr) munmap(cq_ptr, cq_len);
    if (sq_ptr) munmap(sq_ptr, sq_len);
    sq_ptr = cq_ptr = sqe_ptr = nullptr;
    if (ring_fd >= 0) ::close(ring_fd);
    ring_fd = -1;
  }

  bool init(unsigned want) {
    unsigned entries = 4;
    while (entries < want && entries < 4096) entries <<= 1;
    struct io_uring_params p;
    memset(&p, 0, sizeof(p));
    long fd = ::syscall(SYS_io_uring_setup, entries, &p);
    if (fd < 0) return false;
    ring_fd = int(fd);
    sq_len = p.sq_off.array + p.sq_entries * sizeof(unsigned);
    cq_len = p.cq_off.cqes + p.cq_entries * sizeof(io_uring_cqe);
    bool single = (p.features & IORING_FEAT_SINGLE_MMAP) != 0;
    if (single && cq_len > sq_len) sq_len = cq_len;
    void* m = mmap(nullptr, sq_len, PROT_READ | PROT_WRITE,
                   MAP_SHARED | MAP_POPULATE, ring_fd,
                   IORING_OFF_SQ_RING);
    if (m == MAP_FAILED) { shutdown(); return false; }
    sq_ptr = m;
    uint8_t* cqbase = static_cast<uint8_t*>(sq_ptr);
    if (!single) {
      m = mmap(nullptr, cq_len, PROT_READ | PROT_WRITE,
               MAP_SHARED | MAP_POPULATE, ring_fd, IORING_OFF_CQ_RING);
      if (m == MAP_FAILED) { shutdown(); return false; }
      cq_ptr = m;
      cqbase = static_cast<uint8_t*>(cq_ptr);
    }
    sqe_len = p.sq_entries * sizeof(io_uring_sqe);
    m = mmap(nullptr, sqe_len, PROT_READ | PROT_WRITE,
             MAP_SHARED | MAP_POPULATE, ring_fd, IORING_OFF_SQES);
    if (m == MAP_FAILED) { shutdown(); return false; }
    sqe_ptr = m;
    uint8_t* sqbase = static_cast<uint8_t*>(sq_ptr);
    sq_head = reinterpret_cast<unsigned*>(sqbase + p.sq_off.head);
    sq_tail = reinterpret_cast<unsigned*>(sqbase + p.sq_off.tail);
    sq_mask = reinterpret_cast<unsigned*>(sqbase + p.sq_off.ring_mask);
    sq_array = reinterpret_cast<unsigned*>(sqbase + p.sq_off.array);
    sqes = static_cast<io_uring_sqe*>(sqe_ptr);
    cq_head = reinterpret_cast<unsigned*>(cqbase + p.cq_off.head);
    cq_tail = reinterpret_cast<unsigned*>(cqbase + p.cq_off.tail);
    cq_mask = reinterpret_cast<unsigned*>(cqbase + p.cq_off.ring_mask);
    cqes = reinterpret_cast<io_uring_cqe*>(cqbase + p.cq_off.cqes);
    sq_entries = p.sq_entries;
    return true;
  }

  io_uring_sqe* get_sqe() {
    unsigned head = __atomic_load_n(sq_head, __ATOMIC_ACQUIRE);
    unsigned tail = *sq_tail;  // single submitter: plain read is ours
    if (tail - head >= sq_entries) return nullptr;
    unsigned idx = tail & *sq_mask;
    io_uring_sqe* sqe = &sqes[idx];
    memset(sqe, 0, sizeof(*sqe));
    sq_array[idx] = idx;
    __atomic_store_n(sq_tail, tail + 1, __ATOMIC_RELEASE);
    return sqe;
  }

  int enter(unsigned to_submit, unsigned wait_nr) {
    for (;;) {
      long rc = ::syscall(SYS_io_uring_enter, ring_fd, to_submit,
                          wait_nr, wait_nr ? IORING_ENTER_GETEVENTS : 0u,
                          nullptr, 0);
      if (rc >= 0) return int(rc);
      // EINTR: the kernel clamps to_submit to what is actually staged,
      // so re-entering with the same count cannot double-consume.
      if (errno == EINTR) continue;
      return -errno;
    }
  }

  bool pop(io_uring_cqe* out) {
    unsigned head = *cq_head;  // single consumer
    unsigned tail = __atomic_load_n(cq_tail, __ATOMIC_ACQUIRE);
    if (head == tail) return false;
    *out = cqes[head & *cq_mask];
    __atomic_store_n(cq_head, head + 1, __ATOMIC_RELEASE);
    return true;
  }
};

// Runtime probe, cached per process: io_uring may be compiled in yet
// rejected by the running kernel (ENOSYS, seccomp EPERM, sysctl
// io_uring_disabled). HOROVOD_TPU_IOURING=0 forces the poll backend —
// the runtime-fallback knob the fault tests and bench exercise
// without needing an io_uring-less kernel.
bool io_uring_available() {
  static std::atomic<int> cached{0};
  int c = cached.load(std::memory_order_relaxed);
  if (c != 0) return c > 0;
  bool ok = true;
  const char* e = getenv("HOROVOD_TPU_IOURING");
  if (e && e[0] == '0' && e[1] == '\0') ok = false;
  if (ok) {
    UringReactor probe;
    ok = probe.init(4);
  }
  cached.store(ok ? 1 : -1, std::memory_order_relaxed);
  return ok;
}

// Per-thread cached ring + generation counter. Gathers run on one
// controller thread, but thread_local keeps any future caller honest
// (a ring is single-submitter by construction here). The destructor
// closes the ring fd at thread exit. A call that needs more entries
// than the cached ring holds re-initializes it — the kernel cancels
// the old ring's pending requests when its fd closes.
struct GatherRing {
  UringReactor ring;
  uint64_t gen = 0;
  bool live = false;
};

GatherRing& gather_ring() {
  static thread_local GatherRing gr;
  return gr;
}

#endif  // HVD_HAVE_IO_URING

struct GatherCtx {
  const int* fds;
  int n;
  const uint8_t* secret;
  int secret_len;
  uint8_t want_tag;
  void* const* bufs;
  const int64_t* caps;
  int64_t* lens;
  const uint8_t* skip_tags;
  int nskip;
  Deadline dl;
  uint8_t* done;
  double* arrive;
  int32_t* batch_sizes;
  int* nbatches;
  int* dev_idx;
  uint8_t** dev_buf;
  int64_t* dev_len;
  uint8_t* dev_tag;
  int remaining;
};

// Read frames off one readable peer until its DATA frame lands or a
// tolerated stray is drained. A stray (PING) returns to the readiness
// loop instead of camping on this peer — its DATA bytes may not have
// arrived yet and a blocking read here would re-serialize the gather.
// Returns 0 (check *got_data), 1 on deviation (dev_* filled), or
// negative errno.
int gather_read_one(GatherCtx& c, int i, bool* got_data) {
  *got_data = false;
  int fd = c.fds[i];
  uint8_t hdr[5];
  int rc = dl_read(fd, hdr, 5, &c.dl);
  if (rc) return rc;
  uint32_t plen;
  memcpy(&plen, hdr, 4);
  uint8_t tag = hdr[4];
  uint8_t digest[32];
  if (c.secret_len > 0) {
    rc = dl_read(fd, digest, 32, &c.dl);
    if (rc) return rc;
  }
  if (tag == c.want_tag && int64_t(plen) <= c.caps[i]) {
    uint8_t* dst = static_cast<uint8_t*>(c.bufs[i]);
    rc = dl_read(fd, dst, plen, &c.dl);
    if (rc) return rc;
    if (c.secret_len > 0) {
      uint8_t expect[32];
      hmac_sha256(c.secret, size_t(c.secret_len), &tag, dst, plen,
                  expect);
      if (!digest_eq(digest, expect)) return -EBADMSG;
    }
    c.lens[i] = int64_t(plen);
    *got_data = true;
    return 0;
  }
  uint8_t* bounce = nullptr;
  rc = drain_frame(fd, plen, nullptr, 0, tag, c.secret, c.secret_len,
                   digest, &c.dl, &bounce);
  if (rc) return rc;
  if (tag_in(tag, c.skip_tags, c.nskip)) {
    free(bounce);
    return 0;
  }
  // Deviation: out-of-band (METRICS/TRACE/ABORT), wrong tag, or a
  // want_tag payload overflowing caps[i]. Python absorbs the frame and
  // re-enters with done[] intact.
  *c.dev_idx = i;
  *c.dev_buf = bounce;
  *c.dev_len = int64_t(plen);
  *c.dev_tag = tag;
  return 1;
}

int gather_on_ready(GatherCtx& c, int i, int* completed) {
  bool got = false;
  int rc = gather_read_one(c, i, &got);
  if (rc < 0) { *c.dev_idx = i; return rc; }
  if (rc == 1) return 1;
  if (got) {
    c.done[i] = 1;
    c.remaining--;
    if (c.arrive) c.arrive[i] = now_mono();
    (*completed)++;
  }
  return 0;
}

void gather_note_batch(GatherCtx& c, int completed) {
  if (completed <= 0) return;
  c.dl.idle_ms = 0;
  if (c.batch_sizes && c.nbatches && *c.nbatches < c.n)
    c.batch_sizes[(*c.nbatches)++] = completed;
}

int gather_loop_poll(GatherCtx& c) {
  std::vector<struct pollfd> pfs(size_t(c.n));
  std::vector<int> who(size_t(c.n));
  while (c.remaining > 0) {
    int np = 0;
    for (int i = 0; i < c.n; i++) {
      if (c.done[i]) continue;
      pfs[size_t(np)].fd = c.fds[i];
      pfs[size_t(np)].events = POLLIN;
      pfs[size_t(np)].revents = 0;
      who[size_t(np)] = i;
      np++;
    }
    int rc = ::poll(pfs.data(), nfds_t(np),
                    c.dl.timeout_ms >= 0 ? c.dl.interval_ms : -1);
    if (rc < 0) {
      if (errno == EINTR) continue;
      *c.dev_idx = -1;
      return -errno;
    }
    if (rc == 0) {  // idle slice across EVERY pending peer
      if (c.dl.on_idle) c.dl.on_idle();
      c.dl.idle_ms += c.dl.interval_ms;
      if (c.dl.idle_ms >= c.dl.timeout_ms) {
        *c.dev_idx = -1;
        return -ETIMEDOUT;
      }
      continue;
    }
    int completed = 0;
    for (int k = 0; k < np; k++) {
      if (!(pfs[size_t(k)].revents & (POLLIN | POLLERR | POLLHUP)))
        continue;
      rc = gather_on_ready(c, who[size_t(k)], &completed);
      if (rc) { gather_note_batch(c, completed); return rc; }
    }
    gather_note_batch(c, completed);
  }
  return 0;
}

#ifdef HVD_HAVE_IO_URING

int gather_loop_uring(GatherCtx& c) {
  GatherRing& gr = gather_ring();
  if (gr.live && gr.ring.sq_entries < unsigned(c.n) + 2) {
    gr.ring.shutdown();  // cancels the old ring's pending requests
    gr.live = false;
  }
  if (!gr.live) {
    if (!gr.ring.init(unsigned(c.n) + 2)) return gather_loop_poll(c);
    gr.live = true;
  }
  UringReactor& ring = gr.ring;
  // Generation stamp: high 32 bits of user_data. Completions from a
  // PREVIOUS call's leftover POLL_ADDs/timer (timeout or deviation
  // return left them pending) carry an older stamp and are dropped —
  // in particular a stale timer must not tick THIS call's idle clock
  // or clear its timer_armed state.
  const uint64_t gen = ++gr.gen;
  const uint64_t gen_hi = gen << 32;
  const uint32_t timer_lo = ~uint32_t(0);
  std::vector<uint8_t> armed(size_t(c.n), 0);
  bool timer_armed = false;
  struct __kernel_timespec ts;
  while (c.remaining > 0) {
    unsigned to_submit = 0;
    for (int i = 0; i < c.n; i++) {
      if (c.done[i] || armed[size_t(i)]) continue;
      io_uring_sqe* sqe = ring.get_sqe();
      if (!sqe) break;  // ring momentarily full: submit, re-arm later
      sqe->opcode = IORING_OP_POLL_ADD;
      sqe->fd = c.fds[i];
      sqe->poll_events = POLLIN | POLLERR | POLLHUP;
      sqe->user_data = gen_hi | uint32_t(i);
      armed[size_t(i)] = 1;
      to_submit++;
    }
    // One interval timer at a time: it both bounds the wait (idle
    // slice accounting, on_idle fan-out) and keeps stale timers from
    // double-counting silence. The kernel copies the timespec during
    // submission, so the stack slot may be reused the moment enter()
    // returns.
    if (c.dl.timeout_ms >= 0 && !timer_armed) {
      io_uring_sqe* sqe = ring.get_sqe();
      if (sqe) {
        ts.tv_sec = c.dl.interval_ms / 1000;
        ts.tv_nsec = int64_t(c.dl.interval_ms % 1000) * 1000000;
        sqe->opcode = IORING_OP_TIMEOUT;
        sqe->addr = uint64_t(uintptr_t(&ts));
        sqe->len = 1;
        sqe->user_data = gen_hi | timer_lo;
        timer_armed = true;
        to_submit++;
      }
    }
    int rc = ring.enter(to_submit, 1);
    if (rc < 0) { *c.dev_idx = -1; return rc; }
    int completed = 0;
    bool timer_fired = false;
    io_uring_cqe cqe;
    while (ring.pop(&cqe)) {
      if ((cqe.user_data >> 32) != gen) continue;  // stale: drop
      uint32_t lo = uint32_t(cqe.user_data);
      if (lo == timer_lo) {
        timer_armed = false;
        timer_fired = true;
        continue;
      }
      int i = int(lo);
      if (i < 0 || i >= c.n) continue;
      armed[size_t(i)] = 0;  // POLL_ADD is one-shot: re-arm next round
      if (c.done[i]) continue;
      rc = gather_on_ready(c, i, &completed);
      if (rc) { gather_note_batch(c, completed); return rc; }
    }
    if (completed) {
      gather_note_batch(c, completed);
    } else if (timer_fired) {
      if (c.dl.on_idle) c.dl.on_idle();
      c.dl.idle_ms += c.dl.interval_ms;
      if (c.dl.idle_ms >= c.dl.timeout_ms) {
        *c.dev_idx = -1;
        return -ETIMEDOUT;
      }
    }
  }
  return 0;
}

#endif  // HVD_HAVE_IO_URING

// Drain MSG_ZEROCOPY completion notifications from the socket error
// queue until ``expect`` sends are acknowledged. The caller may reuse
// or free the payload buffers the moment hvd_sendv_zc returns, so
// returning with completions outstanding is a use-after-free handed
// to the kernel — this wait is mandatory, bounded by timeout_ms.
int zc_drain(int fd, int expect, int timeout_ms, int* zc_copied) {
  int drained = 0;
  int idle = 0;
  const int slice = 50;
  while (drained < expect) {
    struct msghdr msg;
    memset(&msg, 0, sizeof(msg));
    alignas(struct cmsghdr) char control[256];
    msg.msg_control = control;
    msg.msg_controllen = sizeof(control);
    ssize_t r = ::recvmsg(fd, &msg, MSG_ERRQUEUE | MSG_DONTWAIT);
    if (r < 0) {
      if (errno == EINTR) continue;
      if (errno == EAGAIN || errno == EWOULDBLOCK) {
        struct pollfd pf;
        pf.fd = fd;
        pf.events = 0;  // POLLERR is always reported
        pf.revents = 0;
        int pr = ::poll(&pf, 1, slice);
        if (pr < 0 && errno != EINTR) return -errno;
        if (pr == 0) {
          idle += slice;
          if (timeout_ms >= 0 && idle >= timeout_ms) return -ETIMEDOUT;
        }
        continue;
      }
      return -errno;
    }
    for (struct cmsghdr* cm = CMSG_FIRSTHDR(&msg); cm != nullptr;
         cm = CMSG_NXTHDR(&msg, cm)) {
      if (cm->cmsg_len < CMSG_LEN(sizeof(struct sock_extended_err)))
        continue;
      struct sock_extended_err ee;
      memcpy(&ee, CMSG_DATA(cm), sizeof(ee));
      if (ee.ee_errno != 0 || ee.ee_origin != SO_EE_ORIGIN_ZEROCOPY)
        continue;
      int span = int(ee.ee_data - ee.ee_info) + 1;
      drained += span;
      if ((ee.ee_code & SO_EE_CODE_ZEROCOPY_COPIED) && zc_copied)
        *zc_copied += span;
    }
  }
  return 0;
}

}  // namespace

extern "C" {

int hvd_gather_frames_batched(const int* fds, int n,
                              const uint8_t* secret, int secret_len,
                              uint8_t want_tag, void* const* bufs,
                              const int64_t* caps, int64_t* lens,
                              const uint8_t* skip_tags, int nskip,
                              int timeout_ms, int interval_ms,
                              void (*on_idle)(void),
                              uint8_t* done, double* arrive,
                              int32_t* batch_sizes, int* nbatches,
                              int* dev_idx, uint8_t** dev_buf,
                              int64_t* dev_len, uint8_t* dev_tag) {
  if (!dev_idx || !dev_buf || !dev_len || !dev_tag || !done || !lens)
    return -EINVAL;
  *dev_idx = -1;
  if (n <= 0) return 0;
  GatherCtx c;
  c.fds = fds;
  c.n = n;
  c.secret = secret;
  c.secret_len = secret_len;
  c.want_tag = want_tag;
  c.bufs = bufs;
  c.caps = caps;
  c.lens = lens;
  c.skip_tags = skip_tags;
  c.nskip = nskip;
  c.dl.timeout_ms = timeout_ms;
  c.dl.interval_ms =
      (timeout_ms >= 0 && interval_ms <= 0) ? 100 : interval_ms;
  c.dl.on_idle = on_idle;
  c.dl.idle_ms = 0;
  c.done = done;
  c.arrive = arrive;
  c.batch_sizes = batch_sizes;
  c.nbatches = nbatches;
  c.dev_idx = dev_idx;
  c.dev_buf = dev_buf;
  c.dev_len = dev_len;
  c.dev_tag = dev_tag;
  c.remaining = 0;
  for (int i = 0; i < n; i++)
    if (!done[i]) c.remaining++;
  if (c.remaining == 0) return 0;
#ifdef HVD_HAVE_IO_URING
  if (io_uring_available()) return gather_loop_uring(c);
#endif
  return gather_loop_poll(c);
}

int hvd_sendv_zc(int fd, uint8_t tag, const void* const* bufs,
                 const int64_t* lens, int niov,
                 const uint8_t* secret, int secret_len,
                 int timeout_ms, int* zc_sends, int* zc_copied) {
  if (zc_sends) *zc_sends = 0;
  if (zc_copied) *zc_copied = 0;
  // SO_ZEROCOPY is refused for socket families without zerocopy
  // support (AF_UNIX): the refusal IS the capability probe, and the
  // plain copying send keeps the wire bytes identical.
  int one = 1;
  if (setsockopt(fd, SOL_SOCKET, SO_ZEROCOPY, &one, sizeof(one)) != 0)
    return send_frame_iov(fd, tag, bufs, lens, niov, secret, secret_len);
  int64_t total = 0;
  for (int i = 0; i < niov; i++) {
    if (lens[i] < 0) return -EINVAL;
    total += lens[i];
  }
  if (uint64_t(total) > 0xffffffffull) return -EMSGSIZE;
  uint8_t hdr[5];
  uint32_t n32 = uint32_t(total);
  memcpy(hdr, &n32, 4);  // little-endian hosts only (x86/arm64)
  hdr[4] = tag;
  uint8_t digest[32];
  std::vector<struct iovec> iov;
  iov.reserve(size_t(niov) + 2);
  iov.push_back({hdr, 5});
  if (secret_len > 0) {
    Hmac h(secret, size_t(secret_len));
    h.update(&tag, 1);
    for (int i = 0; i < niov; i++)
      if (lens[i]) h.update(bufs[i], size_t(lens[i]));
    h.final(digest);
    iov.push_back({digest, 32});
  }
  for (int i = 0; i < niov; i++)
    if (lens[i])
      iov.push_back({const_cast<void*>(bufs[i]), size_t(lens[i])});
  // sendv_all's loop with MSG_ZEROCOPY: each successful sendmsg pins
  // the iovecs and owes exactly one completion notification. ENOBUFS
  // (optmem exhausted) retries that sendmsg without the flag.
  struct iovec* cur = iov.data();
  int left = int(iov.size());
  int pending = 0;
  int rc = 0;
  while (left > 0) {
    struct msghdr msg;
    memset(&msg, 0, sizeof(msg));
    msg.msg_iov = cur;
    msg.msg_iovlen = size_t(left);
    ssize_t w = ::sendmsg(fd, &msg, MSG_NOSIGNAL | MSG_ZEROCOPY);
    if (w < 0 && errno == ENOBUFS)
      w = ::sendmsg(fd, &msg, MSG_NOSIGNAL);
    else if (w >= 0)
      pending++;
    if (w < 0) {
      if (errno == EINTR) continue;
      rc = -errno;
      break;
    }
    size_t adv = size_t(w);
    while (left > 0 && adv >= cur->iov_len) {
      adv -= cur->iov_len;
      cur++;
      left--;
    }
    if (left > 0 && adv) {
      cur->iov_base = static_cast<char*>(cur->iov_base) + adv;
      cur->iov_len -= adv;
    }
  }
  if (zc_sends) *zc_sends = pending;
  // Drain even after a send error: any sendmsg that DID go out
  // zero-copy still references caller memory until acknowledged.
  int drc = zc_drain(fd, pending, timeout_ms, zc_copied);
  return rc ? rc : drc;
}

int hvd_relay_frame(int up_fd, const int* child_fds, int nchild,
                    uint8_t want_tag, void* buf, int64_t cap,
                    const uint8_t* secret, int secret_len,
                    const uint8_t* skip_tags, int nskip,
                    int64_t chunk_bytes, int timeout_ms,
                    int interval_ms, int64_t* out_len,
                    uint8_t* out_tag, uint8_t** spill) {
  if (!out_len || !out_tag || !spill) return -EINVAL;
  Deadline dl{timeout_ms,
              (timeout_ms >= 0 && interval_ms <= 0) ? 100 : interval_ms,
              nullptr};
  for (;;) {
    uint8_t hdr[5];
    int rc = dl_read(up_fd, hdr, 5, &dl);
    if (rc) return rc;
    uint32_t plen;
    memcpy(&plen, hdr, 4);
    uint8_t tag = hdr[4];
    uint8_t digest[32];
    if (secret_len > 0) {
      rc = dl_read(up_fd, digest, 32, &dl);
      if (rc) return rc;
    }
    if (tag_in(tag, skip_tags, nskip)) {  // tolerated stray: drop it
      rc = drain_frame(up_fd, plen, nullptr, 0, tag, secret, secret_len,
                       digest, &dl, nullptr);
      if (rc) return rc;
      continue;
    }
    if (tag != want_tag) {  // deviation: hand the whole frame back
      uint8_t* bounce = nullptr;
      rc = drain_frame(up_fd, plen, nullptr, 0, tag, secret, secret_len,
                       digest, &dl, &bounce);
      if (rc) return rc;
      *spill = bounce;
      *out_len = int64_t(plen);
      *out_tag = tag;
      return 2;
    }
    // The expected frame: cut-through. Header and digest go downstream
    // before the first payload byte, then each chunk is relayed as it
    // arrives — a child's read of chunk i overlaps our read of chunk
    // i+1. Children re-verify the digest themselves, so a frame this
    // relay later rejects (-EBADMSG) is rejected by every tier.
    uint8_t* dst;
    bool spilled = false;
    if (int64_t(plen) <= cap) {
      dst = static_cast<uint8_t*>(buf);
    } else {
      dst = static_cast<uint8_t*>(malloc(plen ? plen : 1));
      if (!dst) return -ENOMEM;
      spilled = true;
    }
    uint8_t head[37];
    memcpy(head, hdr, 5);
    size_t head_len = 5;
    if (secret_len > 0) {
      memcpy(head + 5, digest, 32);
      head_len = 37;
    }
    for (int k = 0; k < nchild; k++) {
      rc = write_all(child_fds[k], head, head_len);
      if (rc) {
        if (spilled) free(dst);
        return rc;
      }
    }
    Hmac h(secret, secret_len > 0 ? size_t(secret_len) : 0);
    if (secret_len > 0) h.update(&tag, 1);
    int64_t cb = chunk_bytes > 0 ? chunk_bytes : int64_t(plen);
    int64_t off = 0;
    while (off < int64_t(plen)) {
      int64_t take = int64_t(plen) - off;
      if (take > cb) take = cb;
      rc = dl_read(up_fd, dst + off, size_t(take), &dl);
      if (rc == 0 && secret_len > 0) h.update(dst + off, size_t(take));
      for (int k = 0; rc == 0 && k < nchild; k++)
        rc = write_all(child_fds[k], dst + off, size_t(take));
      if (rc) {
        if (spilled) free(dst);
        return rc;
      }
      off += take;
    }
    if (secret_len > 0) {
      uint8_t expect[32];
      h.final(expect);
      if (!digest_eq(digest, expect)) {
        if (spilled) free(dst);
        return -EBADMSG;
      }
    }
    *out_len = int64_t(plen);
    *out_tag = tag;
    if (spilled) {
      *spill = dst;
      return 1;
    }
    return 0;
  }
}

int hvd_build_flags(void) {
  int flags = 0;
#ifdef HVD_HAVE_IO_URING
  flags |= 1;  // compiled with io_uring support (Makefile probe)
  if (io_uring_available()) flags |= 2;  // running kernel accepts it
#endif
  flags |= 4;  // MSG_ZEROCOPY send path compiled in
  return flags;
}

}  // extern "C"

// ---------------------------------------------------------------------
// Native int8 codec (wire_dtype WIRE_INT8 without the numpy round-trip)
// ---------------------------------------------------------------------

namespace {

// Bit-identical to the numpy reference in common/wire_dtype.py:
//   scale   = float(max|x|) / 127 computed in f64, narrowed to f32 for
//             the header;
//   lanes   = clip(rint(x * T(1/scale)), -127, 127).astype(int8) with
//             the reciprocal narrowed to the array dtype before the
//             multiply (numpy's value-based scalar casting) and
//             round-half-even via rint;
//   residual= compensated - lane * T(header_scale)   (error feedback).
// NaN lanes are platform-defined in both implementations (numpy
// propagates NaN through max; the float->int8 cast of NaN is UB) —
// training guards upstream, the codec does not.
template <typename T>
__attribute__((always_inline)) inline
int quant8_impl(const T* src, int64_t count, const T* res_in,
                T* res_out, uint8_t* out) {
  if (res_in && !res_out) return -EINVAL;
  const T* comp = src;
  T maxabs = T(0);
  if (res_out) {  // stage compensated lanes in the residual buffer
    for (int64_t i = 0; i < count; i++) {
      T v = src[i] + (res_in ? res_in[i] : T(0));
      res_out[i] = v;
      T a = v < T(0) ? -v : v;
      if (a > maxabs) maxabs = a;
    }
    comp = res_out;
  } else {
    for (int64_t i = 0; i < count; i++) {
      T a = src[i] < T(0) ? -src[i] : src[i];
      if (a > maxabs) maxabs = a;
    }
  }
  double scale = count > 0 ? double(maxabs) / 127.0 : 0.0;
  if (scale == 0.0) scale = 1.0;
  float hdr = float(scale);
  memcpy(out, &hdr, 4);
  int8_t* q = reinterpret_cast<int8_t*>(out + 4);
  T inv = T(1.0 / scale);
  T hs = T(hdr);
  for (int64_t i = 0; i < count; i++) {
    T t = std::rint(comp[i] * inv);
    if (t > T(127)) t = T(127);
    if (t < T(-127)) t = T(-127);
    q[i] = int8_t(t);
    // comp may alias res_out: read-then-write of the same lane is fine
    if (res_out) res_out[i] = comp[i] - T(q[i]) * hs;
  }
  return 0;
}

template <typename T>
__attribute__((always_inline)) inline
void dequant8_impl(const uint8_t* src, int64_t count, T* out) {
  float hdr;
  memcpy(&hdr, src, 4);
  const int8_t* q = reinterpret_cast<const int8_t*>(src + 4);
  T s = T(hdr);
  for (int64_t i = 0; i < count; i++) out[i] = T(q[i]) * s;
}

// Runtime ISA dispatch (GNU ifunc): the default x86-64 baseline is
// SSE2, where std::rint cannot vectorize and the codec loses to
// numpy's SIMD kernels; the avx2 clones vectorize rint (vroundps,
// current-mode = round-half-even) and the int8 pack/unpack. Value
// semantics are identical across clones — vroundps IS scalar rint
// lane-wise, and -ffp-contract=off (Makefile) forbids the one
// transform (FMA contraction in the residual) that could split them.
__attribute__((target_clones("avx2", "default")))
int quant8_f32(const float* src, int64_t count, const float* res_in,
               float* res_out, uint8_t* out) {
  return quant8_impl<float>(src, count, res_in, res_out, out);
}

__attribute__((target_clones("avx2", "default")))
int quant8_f64(const double* src, int64_t count, const double* res_in,
               double* res_out, uint8_t* out) {
  return quant8_impl<double>(src, count, res_in, res_out, out);
}

__attribute__((target_clones("avx2", "default")))
void dequant8_f32(const uint8_t* src, int64_t count, float* out) {
  dequant8_impl<float>(src, count, out);
}

__attribute__((target_clones("avx2", "default")))
void dequant8_f64(const uint8_t* src, int64_t count, double* out) {
  dequant8_impl<double>(src, count, out);
}

}  // namespace

extern "C" {

int hvd_quant8(const void* src, int64_t count, int dtype,
               const void* residual, void* residual_out, uint8_t* out) {
  if (count < 0 || !src || !out) return -EINVAL;
  if (dtype == 0)
    return quant8_f32(static_cast<const float*>(src), count,
                      static_cast<const float*>(residual),
                      static_cast<float*>(residual_out), out);
  if (dtype == 1)
    return quant8_f64(static_cast<const double*>(src), count,
                      static_cast<const double*>(residual),
                      static_cast<double*>(residual_out), out);
  return -EINVAL;
}

int hvd_dequant8(const uint8_t* src, int64_t count, int dtype,
                 void* out) {
  if (count < 0 || !src || !out) return -EINVAL;
  if (dtype == 0) {
    dequant8_f32(src, count, static_cast<float*>(out));
    return 0;
  }
  if (dtype == 1) {
    dequant8_f64(src, count, static_cast<double*>(out));
    return 0;
  }
  return -EINVAL;
}

}  // extern "C"
