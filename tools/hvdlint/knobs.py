"""knobs: HOROVOD_* environment reads go through common/config.py and
every knob is documented.

The config surface is a contract — scripts tuned for upstream Horovod
carry over because the names and semantics live in ONE place
(``Config.from_env`` + the ``env_*`` helpers). A stray
``os.environ.get("HOROVOD_...")`` elsewhere silently forks that
contract: different default, different truthiness rules, invisible to
the docs and to ``Config`` snapshots. Two checks:

1. **Routing.** Any *read* of a ``HOROVOD``-prefixed environment
   variable (``os.environ.get``/``[...]``/``os.getenv``/``in
   os.environ`` with a literal key) outside ``common/config.py`` is a
   finding. Writes (``os.environ[k] = v``, ``setdefault``, ``pop``)
   are launcher business and stay legal — the launcher *sets* child
   env; it must not *read* knobs around Config.

2. **Documentation.** Every ``HOROVOD*`` knob name passed to an env
   accessor anywhere in the tree (``env_str``/``env_int``/
   ``env_float``/``env_bool``/``os.environ.get``) must appear in
   ``docs/**/*.md`` or ``README.md``. An undocumented knob is a
   support ticket with extra steps.
"""

from __future__ import annotations

import ast
import glob
import os
from typing import Dict, List, Set, Tuple

from tools.hvdlint.core import Finding, Project, dotted_name

NAME = "knobs"

_ENV_HELPERS = {"env_str", "env_int", "env_float", "env_bool",
                "_env_int", "_env_float", "_env_bool"}


def _literal_key(node: ast.AST) -> str:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return ""


def _is_config_module(modname: str) -> bool:
    return modname.endswith(".config") or modname == "config"


def _env_reads(tree: ast.AST) -> List[Tuple[str, int]]:
    """(knob, line) for literal-keyed environment READS."""
    out: List[Tuple[str, int]] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            d = dotted_name(node.func)
            if d in ("os.environ.get", "os.getenv") and node.args:
                key = _literal_key(node.args[0])
                if key.startswith("HOROVOD"):
                    out.append((key, node.lineno))
        elif isinstance(node, ast.Subscript) and \
                isinstance(node.ctx, ast.Load):
            if dotted_name(node.value) == "os.environ":
                key = _literal_key(node.slice)
                if key.startswith("HOROVOD"):
                    out.append((key, node.lineno))
        elif isinstance(node, ast.Compare) and \
                len(node.ops) == 1 and \
                isinstance(node.ops[0], (ast.In, ast.NotIn)):
            if dotted_name(node.comparators[0]) == "os.environ":
                key = _literal_key(node.left)
                if key.startswith("HOROVOD"):
                    out.append((key, node.lineno))
    return out


def _knob_names(tree: ast.AST) -> Dict[str, int]:
    """Every HOROVOD* literal passed to an env accessor (the documented
    contract surface), knob -> first line."""
    out: Dict[str, int] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        d = dotted_name(node.func) or ""
        tail = d.rsplit(".", 1)[-1]
        if d in ("os.environ.get", "os.getenv") or tail in _ENV_HELPERS:
            key = _literal_key(node.args[0])
            if key.startswith("HOROVOD"):
                out.setdefault(key, node.lineno)
    return out


def _documented_knobs(doc_root: str) -> Set[str]:
    docs: Set[str] = set()
    paths = [os.path.join(doc_root, "README.md")]
    paths += glob.glob(os.path.join(doc_root, "docs", "**", "*.md"),
                       recursive=True)
    for p in paths:
        try:
            with open(p, encoding="utf-8") as f:
                docs.add(f.read())
        except OSError:
            pass
    blob = "\n".join(docs)
    return {w for w in _words(blob) if w.startswith("HOROVOD")}


def _words(text: str) -> Set[str]:
    out: Set[str] = set()
    word = []
    for ch in text:
        if ch.isalnum() or ch == "_":
            word.append(ch)
        elif word:
            out.add("".join(word))
            word = []
    if word:
        out.add("".join(word))
    return out


def run(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    all_knobs: Dict[str, Tuple[str, int]] = {}
    for src in project.files:
        for knob, line in _knob_names(src.tree).items():
            all_knobs.setdefault(knob, (src.path, line))
        if _is_config_module(src.modname):
            continue
        for knob, line in _env_reads(src.tree):
            findings.append(Finding(
                NAME, src.path, line,
                f"direct environment read of {knob} outside "
                f"common/config.py — route it through Config.from_env "
                f"or the config.env_* helpers so defaults, truthiness "
                f"and docs stay in one place"))

    doc_root = project.doc_root()
    if doc_root is not None:
        documented = _documented_knobs(doc_root)
        for knob, (path, line) in sorted(all_knobs.items()):
            if knob not in documented:
                findings.append(Finding(
                    NAME, path, line,
                    f"knob {knob} is read from the environment but "
                    f"appears nowhere in README.md or docs/ — document "
                    f"it or drop it"))
    return findings
