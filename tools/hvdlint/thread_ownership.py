"""thread-ownership: the thread-role graph + the data-race surface.

Every cross-thread bug this tree has shipped so far was a *write/read
pair split across threads with nothing ordering them*: the
``_on_arrivals`` hook read twice while attach_trace could rebind it
between the reads, ``mark_done`` publishing the status store before
the output store that a lock-free ``wait()`` keys on, and
``note_bucket_names`` mutating a set in place that the background
loop reads without the lock. lockdep (PR 5) cannot see any of these —
they are races on plain attributes, not lock misuse — so this
analyzer rebuilds the thread structure statically:

**Role graph.** Every ``threading.Thread(target=...)`` allocation
site defines a role, named by its constant ``name=`` kwarg (the same
names ``common/threadcheck.py`` registers at runtime) or the target's
short name. Call-graph reachability — the same resolution machinery
lock_order uses — assigns each function the set of roles it may run
under; everything else runs as ``main``, and ``main`` propagates
through the call graph like any other role (a helper called from both
the public API and the background loop runs under both).

**Checks**, per instance attribute (``module.Class.attr`` — the
allocation-site identity shared with lockdep and threadcheck) and
per ``global``-declared module variable:

1. *multi-role-write*: compound writes (augmented assignment, item
   store, mutating method call, rebind of a non-fresh value) from two
   or more roles with no common held lock. Plain rebinds of fresh /
   immutable values are exempt: a GIL-atomic flag store
   (``self._running = False``) is the sanctioned stop signal.

2. *unpublished-write*: a field written by exactly one role but read
   from another, where the writes neither hold a common lock nor use
   the snapshot-swap idiom (a single assignment of a freshly built
   object — the only in-place-mutation-free way a lock-free reader
   can observe it).

3. *capture-once*: a rebindable hook (class-body default ``None``,
   rebound outside ``__init__``) read more than once inside one
   function with no lock shared with the rebind sites — the reader
   must capture the hook into a local once, or a concurrent rebind
   lands between the reads (``if self.hook: self.hook()`` is the
   classic TypeError-under-race shape).

4. *publish-order*: a function storing both a lock-free *gate* field
   (one whose value reaches an ``if``/``while`` test or comparison
   with no lock held — the readiness flag wait-style readers poll)
   and a payload field that also has lock-free readers must store the
   payload FIRST; publishing the gate first lets a racing reader
   release a payload that is not yet visible.

Audited exceptions carry field pragmas (justification mandatory)::

    self._table = t  # hvdlint: owned-by=hvd-background -- why safe
    self._snap = new  # hvdlint: snapshot-swapped -- why readers ok

Known blind spots (accepted): calls through stored callbacks do not
extend a role's cone (``entry.callback(...)`` — the runtime checker
covers those paths); Thread targets that are nested functions are not
indexed; writes inside a function that itself spawns a thread are
treated as pre-``start()`` initialization (happens-before via
``Thread.start``); attribute writes on non-``self`` receivers are not
tracked.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tools.hvdlint.core import (
    Finding, FuncInfo, Project, dotted_name, iter_executed,
)

NAME = "thread-ownership"

MAIN_ROLE = "main"

# Infra-typed attributes (locks, queues, events, threads …) have their
# own synchronization story; the lock/teardown analyzers own them.
_INFRA_TAGS = {"lock", "cond", "cond_alias", "event", "queue", "thread",
               "socket", "tlocal"}

_MUTATORS = {"append", "appendleft", "add", "update", "extend", "insert",
             "remove", "discard", "clear", "pop", "popleft", "popitem",
             "setdefault", "sort", "reverse", "write"}

_FRESH_CALLS = {"set", "frozenset", "dict", "list", "tuple", "sorted",
                "bytearray", "type"}


class _Access:
    __slots__ = ("field", "kind", "line", "held", "fresh", "in_test",
                 "scalar")

    def __init__(self, field: str, kind: str, line: int, held: tuple,
                 fresh: bool = False, in_test: bool = False,
                 scalar: bool = False):
        self.field = field
        # kind: read | rebind | aug | item | mutate
        self.kind = kind
        self.line = line
        self.held = held
        self.fresh = fresh
        self.in_test = in_test
        self.scalar = scalar  # rebind of an int/float/bool constant

    @property
    def is_write(self) -> bool:
        return self.kind != "read"


class _FuncFacts:
    def __init__(self):
        self.accesses: List[_Access] = []
        self.calls: List[str] = []
        self.call_sites: List[Tuple[str, tuple]] = []  # (target, held)
        # (role_name, target_qualname | None, line)
        self.spawns: List[Tuple[str, Optional[str], int]] = []
        # lock-free local -> field it snapshots (one-hop dataflow for
        # gate detection: res = self._results.get(h); if res is None:)
        self.snap_locals: Dict[str, str] = {}
        # (field, held-at-test): a gate candidate — only lock-free
        # tests survive once caller-held locks are inherited
        self.gate_marks: List[Tuple[str, tuple]] = []


def _declared_attrs(ci) -> Set[str]:
    """Attributes a class itself declares: class-body assignments plus
    every ``self.x`` store anywhere in its own methods. Cached on the
    ClassIndex (one AST walk per class per run)."""
    cached = getattr(ci, "_to_declared", None)
    if cached is not None:
        return cached
    declared: Set[str] = set()
    for node in ci.node.body:
        if isinstance(node, ast.Assign):
            declared.update(t.id for t in node.targets
                            if isinstance(t, ast.Name))
        elif isinstance(node, ast.AnnAssign) and \
                isinstance(node.target, ast.Name):
            declared.add(node.target.id)
    for node in ast.walk(ci.node):
        if isinstance(node, ast.Attribute) and \
                isinstance(node.ctx, (ast.Store, ast.Del)) and \
                isinstance(node.value, ast.Name) and \
                node.value.id == "self":
            declared.add(node.attr)
    ci._to_declared = declared
    return declared


def _owning_class(project: Project, ci, attr: str, seen=None):
    """The class in ``ci``'s ancestry that declares ``attr``, or None.
    Inheritance must NOT split a field: the ``Controller._on_arrivals``
    hook read from a ``TcpCoordinator`` method is the same storage —
    keying accesses by the accessing class would hide every
    base-declared/derived-read race from all four checks."""
    if seen is None:
        seen = set()
    if id(ci) in seen:
        return None
    seen.add(id(ci))
    if attr in _declared_attrs(ci):
        return ci
    for base in ci.bases:
        if not base:
            continue
        name = base.rsplit(".", 1)[-1]
        bci = ci.module.classes.get(name) or \
            project.index.class_by_name(name)
        if bci is None:
            continue
        owner = _owning_class(project, bci, attr, seen)
        if owner is not None:
            return owner
    return None


def _field_id(info: FuncInfo, attr: str,
              project: Optional[Project] = None) -> Optional[str]:
    if info.cls is None:
        return None
    ci = info.cls
    if project is not None:
        owner = _owning_class(project, ci, attr)
        if owner is not None:
            ci = owner
    return f"{ci.module.src.shortname}.{ci.name}.{attr}"


def _field_tag(info: FuncInfo, attr: str,
               project: Optional[Project] = None) -> Optional[tuple]:
    if info.cls is None:
        return None
    tag = info.cls.attr_types.get(attr)
    if tag is None and project is not None:
        owner = _owning_class(project, info.cls, attr)
        if owner is not None and owner is not info.cls:
            tag = owner.attr_types.get(attr)
    return tag


def _self_attr(node: ast.AST) -> Optional[str]:
    """attr when ``node`` is exactly ``self.<attr>``."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _is_fresh(expr: ast.AST, fresh_locals: Set[str]) -> bool:
    """True when the RHS builds a new (or immutable) object — the
    snapshot-swap requirement: readers see the old object or the new
    one, never a half-mutated hybrid."""
    if isinstance(expr, (ast.Constant, ast.JoinedStr, ast.Dict, ast.List,
                         ast.Set, ast.Tuple, ast.DictComp, ast.ListComp,
                         ast.SetComp, ast.GeneratorExp, ast.BinOp,
                         ast.UnaryOp, ast.Compare, ast.BoolOp,
                         ast.Lambda)):
        return True
    if isinstance(expr, ast.IfExp):
        return _is_fresh(expr.body, fresh_locals) and \
            _is_fresh(expr.orelse, fresh_locals)
    if isinstance(expr, ast.Name):
        return expr.id in fresh_locals
    if isinstance(expr, ast.Call):
        d = dotted_name(expr.func) or ""
        tail = d.rsplit(".", 1)[-1]
        return tail in _FRESH_CALLS or (tail[:1].isupper())
    return False


def _role_of_spawn(call: ast.Call) -> Optional[str]:
    """Role name from the Thread() ``name=`` kwarg, else None (caller
    falls back to the target's short name)."""
    for kw in call.keywords:
        if kw.arg != "name":
            continue
        if isinstance(kw.value, ast.Constant) and \
                isinstance(kw.value.value, str):
            return kw.value.value
        if isinstance(kw.value, ast.JoinedStr):
            parts = [v.value for v in kw.value.values
                     if isinstance(v, ast.Constant)]
            return "".join(str(p) for p in parts).rstrip("-_.") or None
    return None


def _resolve_target(expr: ast.AST, info: FuncInfo,
                    project: Project) -> Optional[str]:
    """Qualname of a Thread ``target=`` callable, or None."""
    resolver = project.resolver
    d = dotted_name(expr)
    if d is None:
        return None
    if d.startswith("self.") and info.cls is not None:
        rest = d.split(".", 1)[1]
        if "." not in rest:
            return resolver._method(info.cls, rest)
        obj, meth = rest.rsplit(".", 1)
        if "." not in obj:
            tag = info.cls.attr_types.get(obj)
            if tag and tag[0] == "class":
                cls = resolver._class_by_qualname(tag[1])
                if cls is not None:
                    return resolver._method(cls, meth)
        return None
    if "." not in d:
        if d in info.module.functions:
            return f"{info.module.modname}.{d}"
        return None
    head, meth = d.rsplit(".", 1)
    if "." not in head and head in info.module.imports:
        target = resolver._module_of(info.module.imports[head])
        if target is not None and meth in target.functions:
            return f"{target.modname}.{meth}"
    return None


def _is_thread_ctor(call: ast.Call, info: FuncInfo) -> bool:
    d = dotted_name(call.func)
    if d is None:
        return False
    return d.rsplit(".", 1)[-1] == "Thread"


class _Walker:
    """Statement walk with held-lock tracking (lock_order's recursion
    shape) that records field accesses, resolvable calls and thread
    spawns for one function."""

    def __init__(self, info: FuncInfo, project: Project,
                 facts: _FuncFacts, globals_declared: Set[str]):
        self.info = info
        self.project = project
        self.facts = facts
        self.globals_declared = globals_declared
        self.fresh_locals: Set[str] = set()
        self.src = info.module.src

    # -- field bookkeeping -------------------------------------------

    def _record(self, attr: str, kind: str, line: int, held: tuple,
                fresh: bool = False, in_test: bool = False,
                is_global: bool = False, scalar: bool = False) -> None:
        if is_global:
            field = f"{self.info.module.src.shortname}.{attr}"
            tag = self.info.module.attr_types.get(attr)
        else:
            field = _field_id(self.info, attr, self.project)
            tag = _field_tag(self.info, attr, self.project)
        if field is None:
            return
        if tag is not None and tag[0] in _INFRA_TAGS:
            return
        if tag is not None and tag[0] == "class" and kind == "mutate":
            return  # method calls on owned objects are the callee's story
        fresh = fresh or line in self.src.snapshot_lines or \
            (line - 1) in self.src.snapshot_lines
        if in_test and kind == "read":
            self.facts.gate_marks.append((field, held))
        self.facts.accesses.append(
            _Access(field, kind, line, held, fresh, in_test, scalar))

    # -- expressions -------------------------------------------------

    def scan_expr(self, expr: ast.AST, held: tuple,
                  in_test: bool = False) -> None:
        if expr is None:
            return
        consumed: Set[int] = set()
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                if _is_thread_ctor(node, self.info):
                    self._record_spawn(node)
                recv = node.func
                if isinstance(recv, ast.Attribute):
                    attr = _self_attr(recv.value)
                    if attr is not None:
                        consumed.add(id(recv.value))
                        kind = "mutate" if recv.attr in _MUTATORS \
                            else "read"
                        self._record(attr, kind, node.lineno, held,
                                     in_test=in_test)
                target = self.project.resolver.resolve_call(
                    node, self.info)
                if target is not None:
                    self.facts.calls.append(target)
                    self.facts.call_sites.append((target, held))
            elif isinstance(node, ast.Compare):
                for fld in self._fields_in(node):
                    self.facts.gate_marks.append((fld, held))
        for node in ast.walk(expr):
            if id(node) in consumed:
                continue
            attr = _self_attr(node)
            if attr is not None and isinstance(node.ctx, ast.Load):
                self._record(attr, "read", node.lineno, held,
                             in_test=in_test)
            elif isinstance(node, ast.Name) and \
                    isinstance(node.ctx, ast.Load) and \
                    node.id in self.globals_declared:
                self._record(node.id, "read", node.lineno, held,
                             in_test=in_test, is_global=True)
        if in_test:
            for node in ast.walk(expr):
                if isinstance(node, ast.Name) and \
                        node.id in self.facts.snap_locals:
                    self.facts.gate_marks.append(
                        (self.facts.snap_locals[node.id], held))

    def _fields_in(self, expr: ast.AST) -> List[str]:
        out = []
        for node in ast.walk(expr):
            attr = _self_attr(node)
            if attr is not None and not attr.isupper():
                fld = _field_id(self.info, attr, self.project)
                tag = _field_tag(self.info, attr, self.project)
                if fld and (tag is None or tag[0] not in _INFRA_TAGS):
                    out.append(fld)
            elif isinstance(node, ast.Name) and \
                    node.id in self.facts.snap_locals:
                out.append(self.facts.snap_locals[node.id])
        return out

    def _record_spawn(self, call: ast.Call) -> None:
        target = None
        for kw in call.keywords:
            if kw.arg == "target":
                target = _resolve_target(kw.value, self.info, self.project)
        role = _role_of_spawn(call)
        if role is None and target is not None:
            role = target.rsplit(".", 1)[-1].lstrip("_")
        if role is not None:
            self.facts.spawns.append((role, target, call.lineno))

    # -- statements --------------------------------------------------

    def _store_target(self, tgt: ast.AST, value: Optional[ast.AST],
                      line: int, held: tuple, aug: bool) -> None:
        attr = _self_attr(tgt)
        if attr is not None:
            if aug:
                self._record(attr, "aug", line, held)
            else:
                fresh = value is not None and \
                    _is_fresh(value, self.fresh_locals)
                scalar = isinstance(value, ast.Constant) and \
                    isinstance(value.value, (bool, int, float))
                self._record(attr, "rebind", line, held, fresh=fresh,
                             scalar=scalar)
            return
        if isinstance(tgt, ast.Subscript):
            sub_attr = _self_attr(tgt.value)
            if sub_attr is not None:
                self._record(sub_attr, "item", line, held)
                return
            if isinstance(tgt.value, ast.Name) and \
                    tgt.value.id in self.globals_declared:
                self._record(tgt.value.id, "item", line, held,
                             is_global=True)
            return
        if isinstance(tgt, ast.Name) and tgt.id in self.globals_declared:
            if aug:
                self._record(tgt.id, "aug", line, held, is_global=True)
            else:
                fresh = value is not None and \
                    _is_fresh(value, self.fresh_locals)
                self._record(tgt.id, "rebind", line, held, fresh=fresh,
                             is_global=True)
            return
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                self._store_target(el, None, line, held, aug)

    def walk(self, stmts, held: tuple) -> None:
        resolver = self.project.resolver
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if isinstance(stmt, ast.Global):
                self.globals_declared.update(stmt.names)
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                new_held = held
                for item in stmt.items:
                    self.scan_expr(item.context_expr, held)
                    lk = resolver.lock_of_expr(item.context_expr,
                                               self.info)
                    if lk is not None:
                        new_held = new_held + (lk[1],)
                self.walk(stmt.body, new_held)
                continue
            if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                targets = stmt.targets if isinstance(stmt, ast.Assign) \
                    else [stmt.target]
                if stmt.value is not None:
                    self.scan_expr(stmt.value, held)
                for tgt in targets:
                    self._store_target(tgt, stmt.value, stmt.lineno,
                                       held, aug=False)
                    if isinstance(tgt, ast.Subscript):
                        self.scan_expr(tgt.slice, held)
                # one-hop snapshot local: res = self._results.get(h)
                if isinstance(stmt, ast.Assign) and \
                        len(targets) == 1 and \
                        isinstance(targets[0], ast.Name) and \
                        stmt.value is not None:
                    name = targets[0].id
                    if not held:
                        flds = self._fields_in(stmt.value)
                        if len(set(flds)) == 1:
                            self.facts.snap_locals[name] = flds[0]
                    if _is_fresh(stmt.value, self.fresh_locals):
                        self.fresh_locals.add(name)
                continue
            if isinstance(stmt, ast.AugAssign):
                self.scan_expr(stmt.value, held)
                self._store_target(stmt.target, None, stmt.lineno, held,
                                   aug=True)
                continue
            if isinstance(stmt, ast.Delete):
                for tgt in stmt.targets:
                    if isinstance(tgt, ast.Subscript):
                        attr = _self_attr(tgt.value)
                        if attr is not None:
                            self._record(attr, "item", stmt.lineno, held)
                        self.scan_expr(tgt.slice, held)
                continue
            if isinstance(stmt, (ast.If, ast.While)):
                self.scan_expr(stmt.test, held, in_test=True)
                self.walk(stmt.body, held)
                self.walk(stmt.orelse, held)
                continue
            if isinstance(stmt, ast.Assert):
                self.scan_expr(stmt.test, held, in_test=True)
                continue
            # everything else: scan child expressions, recurse into
            # child statement blocks under the same held set
            for _f, value in ast.iter_fields(stmt):
                values = value if isinstance(value, list) else [value]
                for v in values:
                    if isinstance(v, ast.stmt):
                        self.walk([v], held)
                    elif isinstance(v, ast.AST):
                        self.scan_expr(v, held)


def _gather(project: Project) -> Dict[str, _FuncFacts]:
    facts: Dict[str, _FuncFacts] = {}
    for qn, info in project.index.functions.items():
        f = _FuncFacts()
        g: Set[str] = set()
        for node in iter_executed(info.node):
            if isinstance(node, ast.Global):
                g.update(node.names)
        _Walker(info, project, f, g).walk(info.node.body, ())
        facts[qn] = f
    return facts


def _inherit_locks(facts: Dict[str, _FuncFacts]) -> Dict[str, Set[str]]:
    """Locks provably held on ENTRY to each function: when every
    resolvable call site of F holds lock L, F's body runs under L
    (the ``_register_idents`` shape — a private helper always invoked
    with the owner's lock held). Functions with no resolvable callers
    (public API, thread targets) inherit nothing."""
    callers: Dict[str, List[Tuple[str, tuple]]] = {}
    for qn, ff in facts.items():
        for tgt, held in ff.call_sites:
            if tgt in facts:
                callers.setdefault(tgt, []).append((qn, held))
    inherited: Dict[str, Set[str]] = {qn: set() for qn in facts}
    for _round in range(10):
        changed = False
        for f, sites in callers.items():
            eff: Optional[Set[str]] = None
            for caller, held in sites:
                s = set(held) | inherited[caller]
                eff = s if eff is None else (eff & s)
            if eff and eff - inherited[f]:
                inherited[f] |= eff
                changed = True
        if not changed:
            break
    return inherited


def role_map(project: Project,
             facts: Optional[Dict[str, _FuncFacts]] = None
             ) -> Dict[str, Set[str]]:
    """qualname -> set of role names the function may run under."""
    if facts is None:
        facts = _gather(project)
    roles: Dict[str, Set[str]] = {qn: set() for qn in facts}
    # thread roles: BFS from every spawn target
    queue: List[Tuple[str, str]] = []
    for f in facts.values():
        for role, target, _line in f.spawns:
            if target is not None and target in roles:
                queue.append((target, role))
    while queue:
        qn, role = queue.pop()
        if role in roles[qn]:
            continue
        roles[qn].add(role)
        for callee in facts[qn].calls:
            if callee in roles:
                queue.append((callee, role))
    # main: everything not exclusively inside a thread cone, propagated
    queue2 = [qn for qn, r in roles.items() if not r]
    for qn in queue2:
        roles[qn].add(MAIN_ROLE)
    while queue2:
        qn = queue2.pop()
        for callee in facts[qn].calls:
            if callee in roles and MAIN_ROLE not in roles[callee]:
                roles[callee].add(MAIN_ROLE)
                queue2.append(callee)
    return roles


class _Field:
    __slots__ = ("writes", "reads", "default_none", "decl_line",
                 "owned_by", "path", "scalar_init")

    def __init__(self):
        self.writes: List[Tuple[str, _Access]] = []   # (qualname, access)
        self.reads: List[Tuple[str, _Access]] = []
        self.default_none = False
        self.decl_line = 0
        self.owned_by: Optional[str] = None
        self.path = ""
        # initialized to an int/float/bool constant: a single-writer
        # augmented counter on it is a GIL-atomic rebind of an
        # immutable value (readers see a stale-but-consistent number)
        self.scalar_init = False


def _collect_fields(project: Project, facts: Dict[str, _FuncFacts]
                    ) -> Dict[str, _Field]:
    fields: Dict[str, _Field] = {}

    def get(fid: str, path: str) -> _Field:
        f = fields.get(fid)
        if f is None:
            f = fields[fid] = _Field()
            f.path = path
        return f

    for qn, ff in facts.items():
        info = project.index.functions[qn]
        path = info.module.src.path
        src = info.module.src
        for a in ff.accesses:
            f = get(a.field, path)
            if a.line in src.owned_by_lines:
                f.owned_by = src.owned_by_lines[a.line]
            elif (a.line - 1) in src.owned_by_lines:
                f.owned_by = src.owned_by_lines[a.line - 1]
            if a.is_write:
                f.writes.append((qn, a))
                if a.scalar and qn.rsplit(".", 1)[-1] == "__init__":
                    f.scalar_init = True
            else:
                f.reads.append((qn, a))
    # class-body defaults: `_on_arrivals = None` declares a rebindable
    # hook; a pragma on the declaration line audits the whole field.
    for mod in project.index.modules.values():
        src = mod.src
        for ci in mod.classes.values():
            for node in ci.node.body:
                if not (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)):
                    continue
                fid = f"{src.shortname}.{ci.name}.{node.targets[0].id}"
                if fid not in fields:
                    continue
                f = fields[fid]
                f.decl_line = node.lineno
                if isinstance(node.value, ast.Constant) and \
                        node.value.value is None:
                    f.default_none = True
                if isinstance(node.value, ast.Constant) and \
                        isinstance(node.value.value, (bool, int, float)):
                    f.scalar_init = True
                for ln in (node.lineno, node.lineno - 1):
                    if ln in src.owned_by_lines:
                        f.owned_by = src.owned_by_lines[ln]
    return fields


def _init_like(qn: str, facts: Dict[str, _FuncFacts]) -> bool:
    """__init__ and thread-spawning functions: their writes precede the
    racing thread's existence (happens-before via Thread.start)."""
    name = qn.rsplit(".", 1)[-1]
    if name in ("__init__", "_reset_for_tests"):
        return True
    return bool(facts[qn].spawns)


def _common_lock(accesses: List[_Access]) -> Optional[str]:
    common: Optional[Set[str]] = None
    for a in accesses:
        s = set(a.held)
        common = s if common is None else (common & s)
        if not common:
            return None
    return sorted(common)[0] if common else None


def _roles_of(qn: str, roles: Dict[str, Set[str]]) -> Set[str]:
    return roles.get(qn, {MAIN_ROLE})


def run(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    facts = _gather(project)
    roles = role_map(project, facts)
    inherited = _inherit_locks(facts)
    for qn, ff in facts.items():
        inh = inherited.get(qn)
        if inh:
            extra = tuple(sorted(inh))
            for a in ff.accesses:
                a.held = a.held + tuple(
                    x for x in extra if x not in a.held)
    fields = _collect_fields(project, facts)

    # gate fields: lock-free tested somewhere (publish-order check);
    # both maps carry the functions doing the lock-free access so the
    # check can require an accessor OUTSIDE the writer.
    gates: Dict[str, Set[str]] = {}
    lockfree_read: Dict[str, Set[str]] = {}
    for qn, ff in facts.items():
        inh = inherited.get(qn) or set()
        for fld, held in ff.gate_marks:
            if not held and not inh:
                gates.setdefault(fld, set()).add(qn)
        for a in ff.accesses:
            if not a.held and not _init_like(qn, facts):
                if a.kind == "read" or a.kind == "mutate":
                    lockfree_read.setdefault(a.field, set()).add(qn)

    for fid, f in sorted(fields.items()):
        if f.owned_by is not None:
            continue
        live_writes = [(qn, a) for qn, a in f.writes
                       if not _init_like(qn, facts)]
        live_reads = [(qn, a) for qn, a in f.reads
                      if not _init_like(qn, facts)]
        if not live_writes:
            continue
        write_roles: Set[str] = set()
        for qn, _a in live_writes:
            write_roles |= _roles_of(qn, roles)

        # -- check 1: compound writes from >= 2 roles, no common lock
        compound = [(qn, a) for qn, a in live_writes
                    if a.kind in ("aug", "item", "mutate")
                    or (a.kind == "rebind" and not a.fresh)]
        if len(write_roles) >= 2 and compound:
            if _common_lock([a for _qn, a in live_writes]) is None:
                qn, a = compound[-1]
                writers = sorted({q.rsplit(".", 1)[-1]
                                  for q, _x in live_writes})
                findings.append(Finding(
                    NAME, f.path, a.line,
                    f"field '{fid}' has compound writes from roles "
                    f"{sorted(write_roles)} ({', '.join(writers)}) with "
                    f"no common lock — concurrent read-modify-write "
                    f"loses updates; guard every write with one lock or "
                    f"audit with '# hvdlint: owned-by=<role> -- why'"))
                continue

        # -- check 2: single-writer field read from another role
        if len(write_roles) >= 1:
            reader_roles: Set[str] = set()
            for qn, _a in live_reads:
                reader_roles |= _roles_of(qn, roles)
            foreign = reader_roles - write_roles
            if foreign and len(write_roles) == 1:
                locked = _common_lock([a for _qn, a in live_writes])
                all_snapshot = all(
                    a.kind == "rebind" and a.fresh
                    for _qn, a in live_writes)
                # a single-writer counter on a scalar-initialized field
                # is a GIL-atomic rebind of an immutable value: readers
                # see a stale-but-consistent number, never a torn one
                scalar_counter = f.scalar_init and all(
                    a.kind == "aug" or (a.kind == "rebind" and a.fresh)
                    for _qn, a in live_writes)
                if locked is None and not all_snapshot \
                        and not scalar_counter:
                    qn, a = live_writes[-1]
                    findings.append(Finding(
                        NAME, f.path, a.line,
                        f"field '{fid}' is written by role "
                        f"{sorted(write_roles)} but read from role(s) "
                        f"{sorted(foreign)} with no lock on the writes "
                        f"and no snapshot-swap (single assignment of a "
                        f"freshly built object) — a lock-free reader "
                        f"can observe a half-mutated value; swap a "
                        f"fresh object, lock the writes, or audit with "
                        f"'# hvdlint: snapshot-swapped -- why'"))

    # -- check 3: capture-once hooks ---------------------------------
    for fid, f in sorted(fields.items()):
        if f.owned_by is not None or not f.default_none:
            continue
        rebinds = [(qn, a) for qn, a in f.writes
                   if a.kind == "rebind"
                   and qn.rsplit(".", 1)[-1] != "__init__"]
        if not rebinds:
            continue
        rebind_funcs = {qn for qn, _a in rebinds}
        rebind_held = [a for _qn, a in rebinds]
        per_func: Dict[str, List[_Access]] = {}
        for qn, a in f.reads:
            if qn in rebind_funcs:
                continue
            per_func.setdefault(qn, []).append(a)
        for qn, reads in sorted(per_func.items()):
            if len(reads) < 2:
                continue
            lk = _common_lock(reads + rebind_held)
            if lk is not None:
                continue
            lines = sorted(a.line for a in reads)
            findings.append(Finding(
                NAME, f.path, lines[1],
                f"hook '{fid}' is read {len(reads)} times in "
                f"{qn.rsplit('.', 1)[-1]} (lines {lines}) while another "
                f"role can rebind it between the reads — capture it "
                f"into a local once (one read) and use the local"))

    # -- check 4: publish-order --------------------------------------
    # Writer shape: a function storing gate + payload under one lock
    # (the mark_done shape — unlocked multi-field writes are already
    # checks 1/2's findings). Reader shape: the gate is tested
    # lock-free by some OTHER function, and the payload has lock-free
    # accessors outside the writer too.
    for qn, ff in sorted(facts.items()):
        if _init_like(qn, facts):
            continue
        by_field: Dict[str, List[_Access]] = {}
        for a in ff.accesses:
            if a.is_write and a.held:
                by_field.setdefault(a.field, []).append(a)
        for gate in sorted(set(by_field) & set(gates)):
            gf = fields.get(gate)
            if gf is not None and gf.owned_by is not None:
                continue
            if not (gates[gate] - {qn}):
                continue  # only the writer itself tests it
            for payload in sorted(set(by_field) & set(lockfree_read)):
                if payload == gate or payload in gates:
                    continue
                if not (lockfree_read[payload] - {qn}):
                    continue
                if _common_lock(by_field[gate] + by_field[payload]) \
                        is None:
                    continue
                first_gate = min(a.line for a in by_field[gate])
                late_payload = [a for a in by_field[payload]
                                if a.line > first_gate]
                if late_payload:
                    findings.append(Finding(
                        NAME,
                        project.index.functions[qn].module.src.path,
                        first_gate,
                        f"{qn.rsplit('.', 1)[-1]} publishes gate field "
                        f"'{gate}' (lock-free readers test it) before "
                        f"storing payload '{payload}' (line "
                        f"{late_payload[0].line}) — a racing reader "
                        f"that sees the gate may read a payload that "
                        f"is not yet visible; store the payload first"))
                    break  # one payload witness per (writer, gate)
    return findings
