"""Incremental-mode result cache for the hvdlint CLI (``--changed``).

Every analyzer in the suite is cross-module by design — the lock
graph, the thread-role cones, the C header mirror all read the WHOLE
tree — so caching findings per file is unsound: an edit in module A
can create or retire a finding reported against module B (rebinding a
lock name, spawning a thread into a new role, deleting a C
declaration). The only sound granularity is the tree: the cache
stores one fingerprint of every scanned file plus the finding list it
produced, and ANY change (edit, rename, add, delete, pragma tweak —
or an edit to the analyzers themselves) discards the whole entry and
re-runs the full suite. On a clean re-run the tier-1 gate pays one
stat() per file instead of a parse + eight analyses.

Validation is two-tier per file: the stat fast path (mtime_ns + size
unchanged ⇒ unchanged) and a sha1 fallback so a touch(1)-style mtime
bump without a content change still replays the cache.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Dict, List, Optional

from tools.hvdlint.core import Finding

VERSION = 1
DEFAULT_CACHE = ".hvdlint_cache.json"


def iter_py(paths: List[str]) -> List[str]:
    """The exact file set core.Project would scan, without parsing."""
    out: List[str] = []
    for root in paths:
        root = os.path.abspath(root)
        if os.path.isfile(root):
            out.append(root)
            continue
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = sorted(d for d in dirnames
                                 if d != "__pycache__")
            out.extend(os.path.join(dirpath, fn)
                       for fn in sorted(filenames)
                       if fn.endswith(".py"))
    return out


def _sha1(path: str) -> str:
    h = hashlib.sha1()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 16), b""):
            h.update(chunk)
    return h.hexdigest()


def _tool_stamp(tool_dir: Optional[str] = None) -> str:
    """Fingerprint of the analyzer suite itself: editing a checker —
    including a data-table edit like jax_compat's API_TABLE — is as
    much a tree change as editing the tree. ``tool_dir`` exists so
    tests can stamp a scratch copy of the suite."""
    here = tool_dir or os.path.dirname(os.path.abspath(__file__))
    h = hashlib.sha1()
    for fn in sorted(os.listdir(here)):
        if fn.endswith(".py"):
            h.update(fn.encode())
            with open(os.path.join(here, fn), "rb") as f:
                h.update(f.read())
    return h.hexdigest()


def fingerprint(paths: List[str]) -> Dict[str, dict]:
    files: Dict[str, dict] = {}
    for p in iter_py(paths):
        st = os.stat(p)
        files[p] = {"mtime": st.st_mtime_ns, "size": st.st_size,
                    "sha1": _sha1(p)}
    return files


def load(paths: List[str], analyzers: List[str],
         cache_file: str) -> Optional[List[Finding]]:
    """Replay the cached findings iff NOTHING changed: same tool
    build, same analyzer selection, same file set, same contents.
    Returns None on any miss (caller re-runs and saves)."""
    try:
        with open(cache_file) as f:
            payload = json.load(f)
    except (OSError, ValueError):
        return None
    if payload.get("version") != VERSION \
            or payload.get("tool") != _tool_stamp() \
            or payload.get("analyzers") != sorted(analyzers):
        return None
    old = payload.get("files", {})
    current = iter_py(paths)
    if set(old) != set(current):
        return None  # add/delete/rename
    for p, rec in old.items():
        try:
            st = os.stat(p)
        except OSError:
            return None
        if st.st_mtime_ns == rec["mtime"] and st.st_size == rec["size"]:
            continue  # stat fast path
        if _sha1(p) != rec["sha1"]:
            return None  # real content change -> full re-run
    return [Finding(**d) for d in payload.get("findings", [])]


def save(paths: List[str], analyzers: List[str], cache_file: str,
         findings: List[Finding]) -> None:
    payload = {"version": VERSION, "tool": _tool_stamp(),
               "analyzers": sorted(analyzers),
               "files": fingerprint(paths),
               "findings": [f.to_dict() for f in findings]}
    tmp = cache_file + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, cache_file)
