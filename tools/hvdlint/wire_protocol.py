"""wire-protocol: coherence checks over the control-plane codec.

Scope: modules named ``wire`` (the project's binary codec). Three bug
classes, each one this repo has actually shipped and review-fixed:

1. **Orphan codec halves.** Every ``serialize_<x>`` must have a
   ``parse_<x>`` and vice versa — a tag you can encode but not decode
   (or the reverse) is a wire protocol only half the world speaks.

2. **Discriminator collisions** (the PR 3 PACKED bug class). All
   one-byte frame discriminators — ``FRAME_*`` integer constants plus
   raw single-byte ``*_PREFIX`` envelope literals — must be pairwise
   distinct, and a raw envelope prefix must sit in the reserved high
   band (>= 0xF0): the first byte of a packed aggregate is a little-
   endian u32 *count*, and a small prefix value is indistinguishable
   from the count byte of a small pack (2 ranks pack to a leading
   0x02, which was exactly FRAME_CACHED_AGG).

3. **Unguarded ``struct.unpack_from``** (the PR 3 truncated-frame bug
   class). Every unpack of network bytes must be dominated by a
   buffer-length guard so a truncated frame raises a transport error
   (ConnectionError) instead of ``struct.error``/IndexError deep in a
   parse. A guard is a preceding call to a ``_need``/``require``-style
   helper or an explicit ``len(...)`` comparison that raises. The same
   applies to raw mask/segment slices: ``int.from_bytes`` over a short
   slice silently yields a WRONG mask, which is worse than a crash.

4. **Kind coverage.** Each ``FRAME_*`` constant must appear in at
   least one ``serialize_*`` and one ``parse_*`` function — a kind
   only one direction knows is an orphan discriminator.

5. **Code-family distinctness.** Single-byte negotiated-attribute
   code families (``WIRE_*`` wire dtypes, ``ALG_*`` algorithm stamps —
   common/wire_dtype.py; ``SPAN_*`` trace span kinds and ``EV_*``
   flight-recorder event codes — common/wire.py, PR 11) must be
   pairwise distinct within their family and fit a u8: these ride
   TRACE/Request/Response frames (and the postmortem ring) as raw
   bytes, and two names sharing a value silently alias two different
   meanings (the compression analog of a FRAME_* collision).

6. **Controller tag distinctness.** Modules named ``controller``
   define the channel frame tags (``TAG_HANDSHAKE`` ... ``TAG_TRACE``)
   as module-level ints: they must be pairwise distinct and u8-ranged,
   or two frame streams silently alias on every channel — the bug
   class a hand-added tag constant can reintroduce in one line.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional

from tools.hvdlint.core import Finding, Project, SourceFile, dotted_name

NAME = "wire-protocol"

GUARD_CALL_NAMES = {"_need", "need", "_require", "require", "_ensure",
                    "ensure", "_check_len", "check_len"}
PREFIX_RESERVED_MIN = 0xF0


def _is_wire_module(src: SourceFile) -> bool:
    return src.shortname == "wire" or src.shortname.startswith("wire_")


def _const_int(node: ast.AST, consts: Dict[str, int]) -> Optional[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return node.value
    if isinstance(node, ast.Name):
        return consts.get(node.id)
    return None


def _collect_discriminators(src: SourceFile):
    """(frame_consts {name: value}, prefixes {name: (value, line, raw)})
    where raw=True means a literal byte not derived from a FRAME_*."""
    frames: Dict[str, int] = {}
    prefixes: Dict[str, tuple] = {}
    for node in src.tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            continue
        name = node.targets[0].id
        val = node.value
        if name.startswith("FRAME_") and isinstance(val, ast.Constant) \
                and isinstance(val.value, int):
            frames[name] = val.value
        elif name.endswith("_PREFIX"):
            if isinstance(val, ast.Constant) and \
                    isinstance(val.value, bytes) and len(val.value) == 1:
                prefixes[name] = (val.value[0], node.lineno, True)
            elif isinstance(val, ast.Call) and \
                    dotted_name(val.func) == "bytes" and val.args:
                # bytes((FRAME_X,)) — derived from a frame constant
                arg = val.args[0]
                elts = arg.elts if isinstance(arg, (ast.Tuple, ast.List)) \
                    else []
                if len(elts) == 1:
                    v = _const_int(elts[0], frames)
                    if v is not None:
                        prefixes[name] = (v, node.lineno, False)
    return frames, prefixes


def _has_guard_before(func: ast.FunctionDef, line: int) -> bool:
    """True when a length guard lexically precedes ``line`` inside
    ``func``: a call to a guard-named helper, or a test (If/Assert/
    While/comparison) that mentions ``len(``."""
    for node in ast.walk(func):
        if getattr(node, "lineno", line) >= line:
            continue
        if isinstance(node, ast.Call):
            d = dotted_name(node.func) or ""
            if d.rsplit(".", 1)[-1] in GUARD_CALL_NAMES:
                return True
        if isinstance(node, (ast.If, ast.Assert, ast.While)):
            for sub in ast.walk(node.test):
                if isinstance(sub, ast.Call) and \
                        dotted_name(sub.func) == "len":
                    if isinstance(node, ast.Assert):
                        return True
                    # an If/While guard must actually bail out
                    if any(isinstance(s, (ast.Raise, ast.Return,
                                          ast.Continue, ast.Break))
                           for s in node.body):
                        return True
    return False


def _is_controller_module(src: SourceFile) -> bool:
    return src.shortname == "controller"


def _check_tag_family(src: SourceFile) -> List[Finding]:
    """TAG_* distinctness + u8 range over a controller module."""
    findings: List[Finding] = []
    values: Dict[int, str] = {}
    for node in src.tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            continue
        cname = node.targets[0].id
        if not cname.startswith("TAG_"):
            continue
        if not (isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, int)):
            continue
        v = node.value.value
        if not 0 <= v <= 255:
            findings.append(Finding(
                NAME, src.path, node.lineno,
                f"channel frame tag {cname} = {v} does not fit the "
                f"u8 the frame header carries"))
        elif v in values:
            findings.append(Finding(
                NAME, src.path, node.lineno,
                f"channel frame tags {values[v]} and {cname} share "
                f"byte value {v:#04x} — two frame streams would "
                f"alias on every channel"))
        else:
            values[v] = cname
    return findings


def run(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for src in project.files:
        if _is_wire_module(src):
            findings.extend(_check_module(src))
        elif _is_controller_module(src):
            findings.extend(_check_tag_family(src))
    return findings


def _check_module(src: SourceFile) -> List[Finding]:
    findings: List[Finding] = []
    serialize: Dict[str, ast.FunctionDef] = {}
    parse: Dict[str, ast.FunctionDef] = {}
    functions: List[ast.FunctionDef] = []
    for node in ast.walk(src.tree):
        if isinstance(node, ast.FunctionDef):
            functions.append(node)
            if node.name.startswith("serialize_"):
                serialize[node.name[len("serialize_"):]] = node
            elif node.name.startswith("parse_"):
                parse[node.name[len("parse_"):]] = node

    # 1 — encode/decode pairing
    for suffix, node in sorted(serialize.items()):
        if suffix not in parse:
            findings.append(Finding(
                NAME, src.path, node.lineno,
                f"serialize_{suffix} has no matching parse_{suffix} — "
                f"a frame the world can emit but never decode"))
    for suffix, node in sorted(parse.items()):
        if suffix not in serialize:
            findings.append(Finding(
                NAME, src.path, node.lineno,
                f"parse_{suffix} has no matching serialize_{suffix} — "
                f"a frame the world expects but never produces"))

    # 2 — discriminator collisions
    frames, prefixes = _collect_discriminators(src)
    seen: Dict[int, str] = {}
    for fname, v in sorted(frames.items()):
        if v in seen:
            findings.append(Finding(
                NAME, src.path, 1,
                f"frame discriminators {seen[v]} and {fname} share "
                f"byte value {v:#04x}"))
        else:
            seen[v] = fname
    for pname, (v, line, raw) in sorted(prefixes.items()):
        if raw:
            if v in seen:
                findings.append(Finding(
                    NAME, src.path, line,
                    f"envelope prefix {pname} ({v:#04x}) collides with "
                    f"frame discriminator {seen[v]} on the same tag"))
            if v < PREFIX_RESERVED_MIN:
                findings.append(Finding(
                    NAME, src.path, line,
                    f"envelope prefix {pname} ({v:#04x}) is below the "
                    f"reserved band (>= {PREFIX_RESERVED_MIN:#04x}): a "
                    f"packed aggregate's leading u32 count byte can "
                    f"alias it (the PACKED relay bug class)"))

    # 3 — unpack/slice guards
    for fn in functions:
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            d = dotted_name(node.func) or ""
            if d.rsplit(".", 1)[-1] == "unpack_from":
                if not _has_guard_before(fn, node.lineno):
                    findings.append(Finding(
                        NAME, src.path, node.lineno,
                        f"struct.unpack_from in {fn.name} is not "
                        f"dominated by a buffer-length guard — a "
                        f"truncated frame raises struct.error instead "
                        f"of a transport error"))
            elif d == "int.from_bytes":
                arg = node.args[0] if node.args else None
                if isinstance(arg, ast.Subscript) and \
                        isinstance(arg.slice, ast.Slice) and \
                        not _has_guard_before(fn, node.lineno):
                    findings.append(Finding(
                        NAME, src.path, node.lineno,
                        f"int.from_bytes over a raw slice in {fn.name} "
                        f"without a length guard — a short buffer "
                        f"silently decodes a WRONG value"))

    # 5 — single-byte code families: WIRE_*/ALG_* (negotiated
    # attributes), SPAN_* (trace span kinds), EV_* (flight recorder
    # event codes) and TENANT_* (service-plane frame kinds,
    # common/tenancy.py) — distinct within each family, u8-ranged
    for family in ("WIRE_", "ALG_", "SPAN_", "EV_", "TENANT_"):
        values: Dict[int, str] = {}
        for node in src.tree.body:
            if not (isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                continue
            cname = node.targets[0].id
            if not cname.startswith(family) or cname.endswith("NAMES"):
                continue
            if not (isinstance(node.value, ast.Constant)
                    and isinstance(node.value.value, int)):
                continue
            v = node.value.value
            if not 0 <= v <= 255:
                findings.append(Finding(
                    NAME, src.path, node.lineno,
                    f"negotiated-attribute code {cname} = {v} does "
                    f"not fit the u8 the wire carries"))
            elif v in values:
                findings.append(Finding(
                    NAME, src.path, node.lineno,
                    f"negotiated-attribute codes {values[v]} and "
                    f"{cname} share byte value {v:#04x} — two "
                    f"verdict names would alias on the wire"))
            else:
                values[v] = cname

    # 4 — kind coverage: every FRAME_* referenced by both directions
    refs: Dict[str, set] = {name: set() for name in frames}
    for direction, table in (("serialize", serialize), ("parse", parse)):
        for fn in table.values():
            for node in ast.walk(fn):
                if isinstance(node, ast.Name) and node.id in refs:
                    refs[node.id].add(direction)
    for fname in sorted(frames):
        used = refs[fname]
        # a constant may legitimately ride through shared helpers; only
        # flag when a direction NEVER sees it
        node_line = 1
        for node in src.tree.body:
            if isinstance(node, ast.Assign) and \
                    isinstance(node.targets[0], ast.Name) and \
                    node.targets[0].id == fname:
                node_line = node.lineno
        for direction in ("serialize", "parse"):
            if direction not in used:
                findings.append(Finding(
                    NAME, src.path, node_line,
                    f"frame kind {fname} never appears in any "
                    f"{direction}_* function — encode/decode halves "
                    f"disagree about the protocol"))
    return findings
