"""lock-order: inter-procedural lock-acquisition graph + hold-blocking.

Two invariants over every ``threading.Lock/RLock/Condition`` (and
``lockdep.*``) site in the tree:

1. **No acquisition-order cycles.** Every ``with <lock>:`` nested
   inside another — directly or through a resolvable call chain — adds
   a directed edge between the two locks' identities. A cycle in that
   graph is a latent deadlock: two threads entering it from different
   corners wedge forever. Acquiring the same non-reentrant lock again
   on the same path is the degenerate one-node cycle and is flagged
   too (self-deadlock).

2. **No blocking calls while holding a lock** that is not on the
   allowlist. Blocking primitives: socket recv/send/accept/connect,
   ``Condition.wait``/``wait_for`` (except on the held condition
   itself, which releases it), ``Event.wait``, ``Thread.join``,
   ``queue.Queue`` get/put (the ``_nowait`` variants are fine),
   ``time.sleep`` and ``subprocess``. A blocking call under a lock
   stalls every thread that touches that lock — the background loop's
   cardinal sin.

Lock identity is the *allocation site* (``module.Class.attr``), not
the instance: two instances of the same class share an identity, the
same grouping runtime lockdep (common/lockdep.py) uses, so a static
finding and a runtime inversion report name the same thing.
Same-identity nesting across *distinct instances* cannot be told from
true self-deadlock statically, so same-identity edges are only flagged
when acquired via ``self``/module globals (provably the same object).

Known blind spots (accepted): calls through unresolvable receivers
(callbacks, duck-typed parameters) are ignored; explicit
``.acquire()``/``.release()`` pairs are not tracked (the codebase uses
``with`` exclusively).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from tools.hvdlint.core import (
    Finding, FuncInfo, Project, dotted_name, iter_executed,
)

NAME = "lock-order"

# Locks that may legitimately be held across blocking calls, with the
# justification a reviewer needs. Keyed by lock identity.
HOLD_BLOCKING_ALLOWLIST = {
    # init()/shutdown() serialize the whole world lifecycle; blocking on
    # the TCP rendezvous / loop join while holding it is the point — no
    # other lock nests inside it and user threads must wait.
    "basics._lock": "init/shutdown serialization; rendezvous blocks by "
                    "design",
    # One-time native-library build: compiles with subprocess under the
    # lock so concurrent local ranks build exactly once.
    "native._lock": "one-shot build serialization across local ranks",
}

_SOCKET_METHODS = {"recv", "recv_into", "recvfrom", "accept", "sendall"}
_SOCKETISH_NAMES = {"sock", "_sock", "conn", "_conn", "server", "_server",
                    "ch", "channel", "_ch", "client"}
_QUEUEISH = {"queue", "_queue", "q"}


class _Blocking:
    """One blocking operation inside a function."""

    __slots__ = ("reason", "line", "exempt_lock")

    def __init__(self, reason: str, line: int,
                 exempt_lock: Optional[str] = None):
        self.reason = reason
        self.line = line
        # a cv.wait() releases (only) its own lock — holding exactly
        # that lock across it is the cv's designed use
        self.exempt_lock = exempt_lock


class _FuncFacts:
    def __init__(self):
        self.acquires: List[Tuple[str, bool, int, bool]] = []
        #   (lock_id, reentrant, line, via_self_or_global)
        self.blocking: List[_Blocking] = []
        self.calls: List[Tuple[str, int]] = []        # anywhere
        # per innermost-held-lock records: (held_stack, node)
        self.under_lock_calls: List[Tuple[tuple, str, int, bool]] = []
        #   last element: call receiver is `self` (same instance proven)
        self.under_lock_blocking: List[Tuple[tuple, _Blocking]] = []
        self.under_lock_acquires: List[Tuple[tuple, str, bool, int, bool]] \
            = []


def _blocking_of_call(call: ast.Call, info: FuncInfo,
                      project: Project) -> Optional[_Blocking]:
    """Classify one Call node as a direct blocking primitive."""
    resolver = project.resolver
    raw = dotted_name(call.func)
    line = call.lineno
    if raw is not None:
        head = raw.split(".")[0]
        if raw in ("time.sleep", "os.system", "os.waitpid"):
            return _Blocking(raw, line)
        if head == "subprocess":
            return _Blocking(raw, line)
    if not isinstance(call.func, ast.Attribute):
        return None
    meth = call.func.attr
    recv = call.func.value
    recv_tag = resolver.type_of_expr(recv, info)
    recv_last = (dotted_name(recv) or "").rsplit(".", 1)[-1]
    if meth in _SOCKET_METHODS:
        return _Blocking(f"socket .{meth}()", line)
    if meth in ("send", "connect"):
        if (recv_tag and recv_tag[0] == "socket") \
                or recv_last in _SOCKETISH_NAMES:
            return _Blocking(f"socket .{meth}()", line)
        return None
    if meth in ("wait", "wait_for"):
        lk = resolver.lock_of_expr(recv, info)
        if lk is not None and lk[0] == "cond":
            return _Blocking(f"Condition.{meth}()", line,
                             exempt_lock=lk[1])
        if recv_tag and recv_tag[0] == "event":
            return _Blocking("Event.wait()", line)
        if recv_tag is None and recv_last.startswith(("_cv", "cv")):
            return _Blocking(f"Condition.{meth}()", line)
        return None
    if meth == "join":
        if recv_tag and recv_tag[0] == "thread":
            return _Blocking("Thread.join()", line)
        return None
    if meth in ("get", "put"):
        if (recv_tag and recv_tag[0] == "queue") \
                or recv_last in _QUEUEISH:
            for kw in call.keywords:
                if kw.arg == "block" and \
                        isinstance(kw.value, ast.Constant) and \
                        kw.value.value is False:
                    return None
            return _Blocking(f"queue .{meth}()", line)
    return None


def _walk_with_locks(stmts, held: tuple, info: FuncInfo,
                     project: Project, facts: _FuncFacts) -> None:
    """Recursive statement walk tracking the stack of held locks."""
    resolver = project.resolver
    for stmt in stmts:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            new_held = held
            for item in stmt.items:
                lk = resolver.lock_of_expr(item.context_expr, info)
                if lk is None:
                    continue
                kind, lock_id, reentrant = lk
                via_self = True  # self attr / module global by lookup
                facts.acquires.append((lock_id, reentrant,
                                       stmt.lineno, via_self))
                if new_held:
                    facts.under_lock_acquires.append(
                        (new_held, lock_id, reentrant, stmt.lineno,
                         via_self))
                new_held = new_held + (lock_id,)
            # expressions inside the with-items themselves run unheld-ish;
            # conservatively analyze them under the OUTER held set
            for item in stmt.items:
                _scan_expr(item.context_expr, held, info, project, facts)
            _walk_with_locks(stmt.body, new_held, info, project, facts)
            continue
        # non-with statements: scan expressions, then recurse into
        # child statement blocks with the same held set
        for field in ast.iter_fields(stmt):
            _, value = field
            values = value if isinstance(value, list) else [value]
            for v in values:
                if isinstance(v, ast.stmt):
                    _walk_with_locks([v], held, info, project, facts)
                elif isinstance(v, ast.AST):
                    _scan_expr(v, held, info, project, facts)


def _scan_expr(expr: ast.AST, held: tuple, info: FuncInfo,
               project: Project, facts: _FuncFacts) -> None:
    for node in ast.walk(expr):
        if isinstance(node, (ast.Lambda,)):
            # a lambda body runs when called, not here — but the common
            # factory-under-lock pattern DOES call it in place; we keep
            # scanning (calls inside resolve or are ignored anyway)
            pass
        if not isinstance(node, ast.Call):
            continue
        blocking = _blocking_of_call(node, info, project)
        if blocking is not None:
            facts.blocking.append(blocking)
            if held:
                facts.under_lock_blocking.append((held, blocking))
            continue
        target = project.resolver.resolve_call(node, info)
        if target is not None:
            facts.calls.append((target, node.lineno))
            if held:
                is_self = (isinstance(node.func, ast.Attribute)
                           and isinstance(node.func.value, ast.Name)
                           and node.func.value.id == "self")
                facts.under_lock_calls.append(
                    (held, target, node.lineno, is_self))


def _gather_facts(project: Project) -> Dict[str, _FuncFacts]:
    facts: Dict[str, _FuncFacts] = {}
    for qn, info in project.index.functions.items():
        f = _FuncFacts()
        _walk_with_locks(info.node.body, (), info, project, f)
        facts[qn] = f
    return facts


def _closure(facts: Dict[str, _FuncFacts]):
    """Fixpoint: per function, the locks it may acquire transitively and
    whether it may block, each with a sample call-chain witness."""
    trans_locks: Dict[str, Dict[str, tuple]] = {}
    trans_block: Dict[str, Optional[tuple]] = {}
    for qn, f in facts.items():
        trans_locks[qn] = {lid: (qn,) for lid, _, _, _ in f.acquires}
        trans_block[qn] = (f.blocking[0].reason, (qn,)) \
            if f.blocking else None
    changed = True
    rounds = 0
    while changed and rounds < 50:
        changed = False
        rounds += 1
        for qn, f in facts.items():
            for callee, _line in f.calls:
                if callee not in facts:
                    continue
                for lid, chain in trans_locks[callee].items():
                    if lid not in trans_locks[qn]:
                        trans_locks[qn][lid] = (qn,) + chain
                        changed = True
                if trans_block[qn] is None and \
                        trans_block[callee] is not None:
                    reason, chain = trans_block[callee]
                    trans_block[qn] = (reason, (qn,) + chain)
                    changed = True
    return trans_locks, trans_block


def _short(qn: str) -> str:
    return ".".join(qn.split(".")[-2:])


def _reentrant(facts: Dict[str, _FuncFacts], lock_id: str) -> bool:
    for f in facts.values():
        for lid, reentrant, _line, _vs in f.acquires:
            if lid == lock_id:
                return reentrant
    return False


def run(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    facts = _gather_facts(project)
    trans_locks, trans_block = _closure(facts)
    path_of = {qn: info.module.src.path
               for qn, info in project.index.functions.items()}

    # -- build the lock-order graph --------------------------------------
    edges: Dict[Tuple[str, str], Tuple[str, int, tuple]] = {}
    for qn, f in facts.items():
        for held, lid, reentrant, line, via_self in f.under_lock_acquires:
            inner = held[-1]
            for h in held:
                if h == lid:
                    if not reentrant and via_self:
                        findings.append(Finding(
                            NAME, path_of[qn], line,
                            f"recursive acquisition of non-reentrant "
                            f"lock '{lid}' in {_short(qn)} — "
                            f"self-deadlock"))
                    continue
                edges.setdefault((h, lid), (qn, line, (qn,)))
        for held, callee, line, is_self in f.under_lock_calls:
            if callee not in facts:
                continue
            direct_callee = {lid for lid, _, _, _ in facts[callee].acquires}
            for lid, chain in trans_locks.get(callee, {}).items():
                for h in held:
                    if h == lid:
                        # Same identity via a call chain: two INSTANCES
                        # of one class are indistinguishable statically,
                        # so only flag when the same object is proven —
                        # a direct self.method() call acquiring a self
                        # attribute lock of the same class.
                        if is_self and lid in direct_callee and \
                                not _reentrant(facts, lid):
                            findings.append(Finding(
                                NAME, path_of[qn], line,
                                f"{_short(qn)} calls {_short(callee)} "
                                f"while holding '{lid}', which "
                                f"{_short(callee)} acquires again — "
                                f"self-deadlock on a non-reentrant "
                                f"lock"))
                        continue
                    edges.setdefault((h, lid), (qn, line, (qn,) + chain))

    # -- cycle detection -------------------------------------------------
    graph: Dict[str, List[str]] = {}
    for (a, b) in edges:
        graph.setdefault(a, []).append(b)
    seen_cycles = set()

    def dfs(start: str):
        stack = [(start, [start])]
        while stack:
            node, path = stack.pop()
            for nxt in graph.get(node, ()):
                if nxt == start:
                    cyc = tuple(path)
                    rot = min(range(len(cyc)),
                              key=lambda i: cyc[i:] + cyc[:i])
                    canon = cyc[rot:] + cyc[:rot]
                    if canon in seen_cycles:
                        continue
                    seen_cycles.add(canon)
                    qn, line, chain = edges[(path[-1], start)]
                    order = " -> ".join(canon + (canon[0],))
                    findings.append(Finding(
                        NAME, path_of[qn], line,
                        f"lock acquisition-order cycle: {order} "
                        f"(edge witnessed in {_short(qn)} via "
                        f"{' -> '.join(_short(c) for c in chain)})"))
                elif nxt not in path:
                    stack.append((nxt, path + [nxt]))

    for node in list(graph):
        dfs(node)

    # -- blocking while holding a lock -----------------------------------
    for qn, f in facts.items():
        for held, blocking in f.under_lock_blocking:
            bad = [h for h in held
                   if h != blocking.exempt_lock
                   and h not in HOLD_BLOCKING_ALLOWLIST]
            if bad:
                findings.append(Finding(
                    NAME, path_of[qn], blocking.line,
                    f"blocking call ({blocking.reason}) while holding "
                    f"lock(s) {sorted(bad)} in {_short(qn)} — a stalled "
                    f"peer wedges every thread contending on them"))
        for held, callee, line, _is_self in f.under_lock_calls:
            tb = trans_block.get(callee)
            if tb is None:
                continue
            reason, chain = tb
            bad = [h for h in held if h not in HOLD_BLOCKING_ALLOWLIST]
            if bad:
                findings.append(Finding(
                    NAME, path_of[qn], line,
                    f"call chain {' -> '.join(_short(c) for c in (qn,) + chain)} "
                    f"may block ({reason}) while {_short(qn)} holds "
                    f"lock(s) {sorted(bad)}"))
    return findings
