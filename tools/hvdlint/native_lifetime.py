"""native-lifetime: buffer ownership across the GIL-releasing boundary.

The native core (``native/hvdtpu.cc``) runs every ``hvd_*`` entry
point with the GIL released; the Python side hands it raw addresses
(``arr.ctypes.data``), ctypes callback thunks, and iovec bundles built
over fusion-arena views. None of those carry a reference — the
address is an integer, the thunk a C function pointer — so the PYTHON
expression that produced them must keep the owner alive for as long
as the native side may touch the memory. Three historical bug
classes, each with a fixed exemplar in the tree:

1. **Inline temporaries.** ``X(...).ctypes.data`` takes the address
   of an array nothing names: the temporary is reclaimed when the
   statement ends (or, for a nested call argument, possibly before
   the outer call even runs), and the native side scribbles through
   freed memory. Only *rooted* expressions are provably alive —
   ``out.ctypes.data`` (local), ``self._buf.ctypes.data``
   (attribute chain), ``result[off:off + n].ctypes.data`` (a view
   whose base a name keeps alive, steady.py's scatter loop). The
   rule is therefore syntactic: walk off ``.ctypes.data`` /
   ``.ctypes.data_as`` through attributes and subscripts; a ``Call``
   at the root is flagged, a ``Name``/attribute chain is not.

2. **Callback thunks without a long-lived owner.** A CFUNCTYPE
   instance IS the executable thunk; if the only reference is a call
   argument or a dropped local, a native entry that re-enters it
   after Python moves on calls through freed code (the NULL_ON_IDLE
   class — native.py's module-level ``NULL_ON_IDLE = ON_IDLE_FUNC(0)``
   is the fixed form, controller's ``self._steady_on_idle = ...`` the
   instance-owned one). Instantiating a known functype anywhere other
   than a module-level or ``self.``-attribute assignment is flagged.

3. **Arena pointer bundles cached without a generation key.** A
   FusionArena grows by REALLOCATING (``ensure`` bumps
   ``generation``); views taken before the growth stay valid (numpy
   keeps the old base alive) but point into the OLD allocation — a
   memoized iovec built from them silently diverges from the views a
   resubmission writes through. Any function that builds ctypes
   pointers over arena views (``.typed(...)`` / ``.view(...)`` on a
   receiver it also ``ensure``s) and stores them in a ``cache``
   container must read ``.generation`` to key the bundle
   (steady.py:_c_coord is the canonical shape).

Residual blind spots (accepted): functype TYPES are recognized only
when bound at module level to a ``ctypes.CFUNCTYPE(...)`` result
(per-call ``CFUNCTYPE(...)(f)`` double-calls are caught, aliased
types through locals are not); check 3 is function-scoped — a bundle
built in one function and cached in another is invisible; ownership
through containers (a list that outlives the call holding the
temporary) is not modeled, so a true positive of class 1 may have a
container keeping it alive — audit before suppressing.
"""

from __future__ import annotations

import ast
from typing import List, Set

from tools.hvdlint.core import Finding, Project, SourceFile, dotted_name

NAME = "native-lifetime"


# -- shared walking helpers -----------------------------------------------

def _root(node: ast.AST) -> ast.AST:
    """Strip attribute/subscript/starred wrappers down to the owning
    expression: the thing whose liveness keeps the pointer valid."""
    while True:
        if isinstance(node, ast.Attribute):
            node = node.value
        elif isinstance(node, ast.Subscript):
            node = node.value
        elif isinstance(node, ast.Starred):
            node = node.value
        else:
            return node


def _describe(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover - unparse is total on 3.9+
        return "<expr>"


def _is_ptr_attr(node: ast.AST) -> bool:
    """True for ``X.ctypes.data`` / ``X.ctypes.data_as`` accesses."""
    return (isinstance(node, ast.Attribute)
            and node.attr in ("data", "data_as")
            and isinstance(node.value, ast.Attribute)
            and node.value.attr == "ctypes")


# -- check 1: inline temporaries ------------------------------------------

def _check_temporaries(src: SourceFile, findings: List[Finding]) -> None:
    for node in ast.walk(src.tree):
        if not _is_ptr_attr(node):
            continue
        owner = _root(node.value.value)
        if isinstance(owner, ast.Call):
            findings.append(Finding(
                NAME, src.path, node.lineno,
                f"pointer taken from an unnamed temporary "
                f"({_describe(node.value.value)}): the array is "
                f"reclaimed when the statement ends, and the "
                f"GIL-releasing native side writes through freed "
                f"memory — bind it to a name that outlives the call"))


# -- check 2: CFUNCTYPE ownership -----------------------------------------

def _functype_names(project: Project) -> Set[str]:
    """Names bound at module level to a ctypes.CFUNCTYPE(...) result,
    anywhere in the scanned tree (e.g. native.ON_IDLE_FUNC)."""
    names: Set[str] = set()
    for src in project.files:
        for node in src.tree.body:
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)):
                continue
            callee = dotted_name(node.value.func) or ""
            if callee.rsplit(".", 1)[-1] != "CFUNCTYPE":
                continue
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    names.add(tgt.id)
    return names


def _is_functype_call(node: ast.Call, functypes: Set[str]) -> bool:
    callee = dotted_name(node.func) or ""
    if callee and callee.rsplit(".", 1)[-1] in functypes:
        return True
    # Per-call double construction: ctypes.CFUNCTYPE(None)(f).
    if isinstance(node.func, ast.Call):
        inner = dotted_name(node.func.func) or ""
        if inner.rsplit(".", 1)[-1] == "CFUNCTYPE":
            return True
    return False


def _owned_target(stmt: ast.stmt) -> bool:
    """True when the statement stores its value on a self attribute —
    the instance owns the thunk for its own lifetime."""
    if not isinstance(stmt, (ast.Assign, ast.AnnAssign)):
        return False
    targets = stmt.targets if isinstance(stmt, ast.Assign) \
        else [stmt.target]
    return any(isinstance(t, ast.Attribute)
               and isinstance(t.value, ast.Name) and t.value.id == "self"
               for t in targets)


def _check_functypes(src: SourceFile, functypes: Set[str],
                     findings: List[Finding]) -> None:
    # Module-level assignments are long-lived by construction; self-
    # attribute stores are owned for the instance's life. Collect the
    # line spans of both so instantiations inside them pass.
    ok_lines: Set[int] = set()
    for node in src.tree.body:
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            for sub in ast.walk(node):
                ok_lines.add(getattr(sub, "lineno", 0))
    for node in ast.walk(src.tree):
        if isinstance(node, (ast.Assign, ast.AnnAssign)) \
                and _owned_target(node):
            for sub in ast.walk(node):
                ok_lines.add(getattr(sub, "lineno", 0))

    for node in ast.walk(src.tree):
        if not (isinstance(node, ast.Call)
                and _is_functype_call(node, functypes)):
            continue
        if node.lineno in ok_lines:
            continue
        findings.append(Finding(
            NAME, src.path, node.lineno,
            f"CFUNCTYPE thunk built without a long-lived owner "
            f"({_describe(node)}): a native entry that re-enters the "
            f"callback after this frame unwinds calls through freed "
            f"code — store it at module level (the NULL_ON_IDLE "
            f"pattern) or on self before handing it to the core"))


# -- check 3: arena pointer caches ----------------------------------------

def _arena_receivers(fn: ast.AST) -> Set[str]:
    """Names the function treats as a growable arena: receivers of
    .ensure()/.typed() calls. '.view' alone is too generic
    (memoryview/ndarray both have it) to classify a receiver."""
    strong: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and isinstance(node.func.value, ast.Name) \
                and node.func.attr in ("ensure", "typed"):
            strong.add(node.func.value.id)
    return strong


def _check_arena_caches(src: SourceFile, findings: List[Finding]) -> None:
    for fn in ast.walk(src.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        arenas = _arena_receivers(fn)
        if not arenas:
            continue
        takes_ptr = any(_is_ptr_attr(n) for n in ast.walk(fn))
        stores_cache = any(
            isinstance(n, (ast.Assign, ast.AnnAssign))
            and any(isinstance(t, ast.Subscript)
                    and "cache" in _describe(t.value).lower()
                    for t in (n.targets if isinstance(n, ast.Assign)
                              else [n.target]))
            for n in ast.walk(fn))
        if not (takes_ptr and stores_cache):
            continue
        if any(isinstance(n, ast.Attribute) and n.attr == "generation"
               for n in ast.walk(fn)):
            continue
        findings.append(Finding(
            NAME, src.path, fn.lineno,
            f"{fn.name} caches ctypes pointers over arena views "
            f"({', '.join(sorted(arenas))}) without keying on "
            f".generation: ensure() REALLOCATES on growth, so a "
            f"resubmission writes the new allocation while the "
            f"memoized iovec still points at the old one — key the "
            f"bundle on the arena's generation (_c_coord pattern)"))


def run(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    functypes = _functype_names(project)
    for src in project.files:
        _check_temporaries(src, findings)
        _check_functypes(src, functypes, findings)
        _check_arena_caches(src, findings)
    return findings
