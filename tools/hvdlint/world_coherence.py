"""world-coherence: world-replicated state mutates only behind
``@world_coherent`` sites.

PR 3's response cache works because every structural mutation (slot
assignment, LRU order, eviction, epoch) is driven ONLY by world-
identical events — the broadcast response stream and the coordinator's
grant/invalidate masks — applied in one canonical order on every rank.
That invariant lived in prose; this analyzer makes it a check:

* An attribute is declared world-replicated by a trailing
  ``# hvdlint: world-replicated`` comment on its initializing
  assignment (ResponseCache ``epoch``/``_lru``/``_slots``/``_free``,
  the runtime's steady predictor).

* Any function that mutates such an attribute — assignment, augmented
  assignment, subscript store/delete, a mutating method call
  (``append``/``pop``/``move_to_end``/...), or passing it to
  ``heapq.heappush``/``heappop`` — or that calls a *mutator method* of
  the owning class on a typed receiver, must be **coverage-reachable**:
  it carries ``@world_coherent`` itself, or every one of its in-project
  callers does (transitively). The decorator (exported by
  ``horovod_tpu.common.invariants``) marks exactly the functions whose
  inputs are world-identical by construction; anything else reaching a
  mutation is a latent divergence — one rank's cache marching to a
  different drummer.

The owning class's ``__init__`` (construction) is exempt; so is the
declaring assignment itself. Reads are always fine — divergence needs
a write.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tools.hvdlint.core import (
    Finding, FuncInfo, Project, dotted_name, iter_executed,
)

NAME = "world-coherence"

MUTATING_METHODS = {
    "append", "extend", "insert", "pop", "popitem", "popleft",
    "appendleft", "clear", "remove", "discard", "add", "update",
    "setdefault", "move_to_end", "push",
}
_HEAP_FUNCS = {"heapq.heappush", "heapq.heappop", "heapq.heapreplace",
               "heapq.heappushpop"}


def _declared(project: Project) -> Dict[str, Set[str]]:
    """class qualname -> set of world-replicated attr names."""
    out: Dict[str, Set[str]] = {}
    for mod in project.index.modules.values():
        for ci in mod.classes.values():
            if ci.replicated_attrs:
                out[ci.qualname] = set(ci.replicated_attrs)
    return out


def _is_world_coherent(info: FuncInfo) -> bool:
    return any(d.rsplit(".", 1)[-1] == "world_coherent"
               for d in info.decorators)


def _attr_of(node: ast.AST) -> Optional[str]:
    """'X' for a plain ``self.X`` expression."""
    d = dotted_name(node)
    if d and d.startswith("self.") and d.count(".") == 1:
        return d.split(".", 1)[1]
    return None


def _direct_mutations(info: FuncInfo, attrs: Set[str]
                      ) -> List[Tuple[str, int]]:
    """(attr, line) for every mutation of a declared attr of the
    function's own class."""
    out: List[Tuple[str, int]] = []
    for node in iter_executed(info.node):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                a = _attr_of(t)
                if a in attrs:
                    out.append((a, node.lineno))
                if isinstance(t, ast.Subscript):
                    a = _attr_of(t.value)
                    if a in attrs:
                        out.append((a, node.lineno))
        elif isinstance(node, ast.AugAssign):
            a = _attr_of(node.target)
            if a in attrs:
                out.append((a, node.lineno))
            if isinstance(node.target, ast.Subscript):
                a = _attr_of(node.target.value)
                if a in attrs:
                    out.append((a, node.lineno))
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                a = _attr_of(t)
                if a in attrs:
                    out.append((a, node.lineno))
                if isinstance(t, ast.Subscript):
                    a = _attr_of(t.value)
                    if a in attrs:
                        out.append((a, node.lineno))
        elif isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr in MUTATING_METHODS:
                a = _attr_of(f.value)
                if a in attrs:
                    out.append((a, node.lineno))
            d = dotted_name(f)
            if d in _HEAP_FUNCS:
                for arg in node.args[:1]:
                    a = _attr_of(arg)
                    if a in attrs:
                        out.append((a, node.lineno))
    return out


def run(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    declared = _declared(project)
    if not declared:
        return findings
    index = project.index
    resolver = project.resolver

    # mutator functions: qualname -> (owner class, attr, line)
    mutators: Dict[str, Tuple[str, str, int]] = {}
    for qn, info in index.functions.items():
        if info.cls is None or info.cls.qualname not in declared:
            continue
        if info.node.name == "__init__":
            continue  # construction precedes replication
        hits = _direct_mutations(info, declared[info.cls.qualname])
        if hits:
            attr, line = hits[0]
            mutators[qn] = (info.cls.qualname, attr, line)

    # functions calling a mutator METHOD of an owning class on a typed
    # receiver also count as mutation sites
    mutator_methods: Dict[str, Set[str]] = {}
    for qn in mutators:
        cls_qual, _, mname = qn.rpartition(".")
        mutator_methods.setdefault(cls_qual, set()).add(mname)

    # reverse call graph over resolvable calls
    callers: Dict[str, Set[str]] = {}
    calls_of: Dict[str, Set[str]] = {}
    for qn, info in index.functions.items():
        targets: Set[str] = set()
        for node in iter_executed(info.node):
            if isinstance(node, ast.Call):
                t = resolver.resolve_call(node, info)
                if t is not None:
                    targets.add(t)
                    callers.setdefault(t, set()).add(qn)
        calls_of[qn] = targets

    # coverage: a function is covered when annotated, or when it HAS
    # callers and every caller is covered.
    memo: Dict[str, Optional[bool]] = {}

    def covered(qn: str) -> bool:
        state = memo.get(qn)
        if state is not None:
            return state
        memo[qn] = False  # cycle guard: a caller loop is not coverage
        info = index.functions.get(qn)
        if info is not None and _is_world_coherent(info):
            memo[qn] = True
            return True
        cs = callers.get(qn, set())
        # coverage flows down the call graph; an uncalled, unannotated
        # function is uncovered by definition (tests and external API
        # consumers are outside the scanned set on purpose).
        result = bool(cs) and all(covered(c) for c in cs)
        memo[qn] = result
        return result

    reported: Set[str] = set()

    def report(qn: str, why: str, line: int) -> None:
        if qn in reported:
            return
        reported.add(qn)
        info = index.functions[qn]
        findings.append(Finding(
            NAME, info.module.src.path, line,
            f"{qn.split('.', 2)[-1]} {why}, but is reachable outside "
            f"@world_coherent call chains — a rank-local caller could "
            f"diverge world-replicated state"))

    for qn, (cls_qual, attr, line) in mutators.items():
        if not covered(qn):
            report(qn, f"mutates world-replicated "
                       f"'{cls_qual.rsplit('.', 1)[-1]}.{attr}'", line)

    for qn, info in index.functions.items():
        if qn in mutators:
            continue
        for node in iter_executed(info.node):
            if not isinstance(node, ast.Call):
                continue
            t = resolver.resolve_call(node, info)
            if t is None:
                continue
            cls_qual, _, mname = t.rpartition(".")
            if cls_qual in mutator_methods and \
                    mname in mutator_methods[cls_qual] and \
                    (info.cls is None or info.cls.qualname != cls_qual):
                if not covered(qn):
                    report(qn, f"calls world-replicated mutator "
                               f"{cls_qual.rsplit('.', 1)[-1]}.{mname}",
                           node.lineno)
    return findings
