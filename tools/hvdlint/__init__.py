"""hvdlint — project-invariant static analysis for horovod_tpu.

Five analyzers, each encoding an invariant this codebase has already
paid a review-found bug for (see docs/static_analysis.md):

=================  ========================================================
lock-order         inter-procedural lock-acquisition graph: order cycles,
                   self-deadlock, blocking calls under a held lock
wire-protocol      codec coherence: serialize/parse pairing, discriminator
                   byte collisions (the PACKED bug class), length guards
                   on every unpack
world-coherence    world-replicated state (response cache, steady
                   predictor) mutates only behind @world_coherent sites
teardown           multi-step cleanup in finally blocks / close functions
                   is stage-guarded
knobs              HOROVOD_* env reads route through common/config.py and
                   every knob is documented
=================  ========================================================

Run ``python -m tools.hvdlint horovod_tpu`` (add ``--json`` for machine
output). The runtime counterpart — the lockdep mode armed by
``HOROVOD_TPU_LOCKCHECK=1`` — lives in ``horovod_tpu/common/lockdep.py``.
"""

from tools.hvdlint.core import Finding, get_analyzers, lint_paths

__all__ = ["Finding", "get_analyzers", "lint_paths"]
