"""CLI: ``python -m tools.hvdlint [paths...] [--analyzer a,b] [--json]``.

Exit codes: 0 clean, 1 findings, 2 usage/internal error.
"""

from __future__ import annotations

import argparse
import json
import sys

from tools.hvdlint import cache
from tools.hvdlint.core import get_analyzers, lint_paths


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.hvdlint",
        description="horovod_tpu project-invariant static analysis")
    parser.add_argument("paths", nargs="*", default=["horovod_tpu"],
                        help="packages/files to analyze "
                             "(default: horovod_tpu)")
    parser.add_argument("--analyzer", "-a", default="",
                        help="comma-separated subset of analyzers")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable findings on stdout")
    parser.add_argument("--list", action="store_true",
                        help="list available analyzers and exit")
    parser.add_argument("--changed", action="store_true",
                        help="incremental mode: replay the cached "
                             "result when no scanned file (or "
                             "analyzer) changed; any change re-runs "
                             "the FULL suite — per-file caching is "
                             "unsound for cross-module analyzers")
    parser.add_argument("--cache-file", default=None,
                        help="cache location for --changed "
                             "(default: .hvdlint_cache.json)")
    args = parser.parse_args(argv)

    if args.list:
        for name in sorted(get_analyzers()):
            print(name)
        return 0
    analyzers = [a.strip() for a in args.analyzer.split(",") if a.strip()] \
        or None
    paths = args.paths or ["horovod_tpu"]
    selected = analyzers or sorted(get_analyzers())
    cache_file = args.cache_file or cache.DEFAULT_CACHE
    try:
        findings = cache.load(paths, selected, cache_file) \
            if args.changed else None
        if findings is None:
            findings = lint_paths(paths, analyzers)
            if args.changed:
                cache.save(paths, selected, cache_file, findings)
    except (ValueError, OSError, SyntaxError) as e:
        print(f"hvdlint: {e}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps({"findings": [f.to_dict() for f in findings],
                          "count": len(findings)}, indent=2))
    else:
        for f in findings:
            print(f.render())
        print(f"hvdlint: {len(findings)} finding(s)")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
