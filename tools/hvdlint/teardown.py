"""teardown: multi-step cleanup must be stage-guarded.

The PR 4 bug class: ``Runtime._background_loop``'s ``finally`` ran the
finalizer drain, the shutdown fan-out callbacks and the timeline flush
in sequence — and a raising drain or user callback silently skipped
``Timeline.shutdown``, leaving the trace of exactly the aborted run you
most wanted to inspect as an unterminated JSON fragment. The rule:

* In any ``finally`` block — and in any function named ``close`` /
  ``shutdown`` / ``teardown`` / ``__exit__`` (the shutdown paths) —
  with **two or more** cleanup stages, every stage must be
  individually guarded (wrapped in its own ``try``), because a raise
  in one stage must not skip the ones after it.

* A *cleanup stage* is a top-level statement invoking a cleanup-shaped
  call: ``.close() .shutdown() .join() .drain() .stop() .terminate()
  .kill() .cancel() .release() .unlink() .callback()``. Bookkeeping
  (assignments, ``.set()``, logging) does not count as a stage.

* In a named cleanup *function* the last stage may propagate (raising
  from the final step of ``close()`` is legitimate API behavior); in a
  ``finally`` block every stage must be guarded — an exception escaping
  a ``finally`` also clobbers whatever exception was already in
  flight, which is how the original failure disappears from logs.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Tuple

from tools.hvdlint.core import Finding, Project, dotted_name

NAME = "teardown"

CLEANUP_CALL_NAMES = {
    "close", "shutdown", "join", "drain", "stop", "terminate", "kill",
    "cancel", "disconnect", "unlink", "cleanup", "callback",
}
CLEANUP_FUNC_NAMES = {"close", "shutdown", "teardown", "__exit__"}


def _cleanup_calls(stmt: ast.stmt) -> List[ast.Call]:
    """Cleanup-shaped calls in a statement, NOT descending into nested
    try-guards (those are already staged) or nested defs."""
    out: List[ast.Call] = []
    stack: List[ast.AST] = [stmt]
    while stack:
        node = stack.pop()
        if node is not stmt and isinstance(node, ast.Try):
            continue
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue
        if isinstance(node, ast.Call):
            name = None
            if isinstance(node.func, ast.Attribute):
                name = node.func.attr
            elif isinstance(node.func, ast.Name):
                name = node.func.id
            if name in CLEANUP_CALL_NAMES:
                d = dotted_name(node.func) or ""
                # str.join / os.path.join style false friends
                if not d.startswith(("os.", "str.", '"', "'")):
                    out.append(node)
        stack.extend(ast.iter_child_nodes(node))
    return out


def _stages(body: List[ast.stmt]) -> List[Tuple[ast.stmt, bool,
                                                Optional[ast.Call]]]:
    """(statement, guarded, first unguarded cleanup call) per top-level
    statement that contains at least one cleanup call."""
    stages = []
    for stmt in body:
        if isinstance(stmt, ast.Try):
            # guarded stage if it has handlers; its own finally/else are
            # the statement's business
            inner = []
            for s in stmt.body:
                inner.extend(_cleanup_calls(s))
            if inner or any(_cleanup_calls(s) for h in stmt.handlers
                            for s in h.body):
                stages.append((stmt, bool(stmt.handlers),
                               inner[0] if inner else None))
            continue
        calls = _cleanup_calls(stmt)
        if calls:
            stages.append((stmt, False, calls[0]))
    return stages


def _check_block(body: List[ast.stmt], path: str, where: str,
                 allow_last_unguarded: bool,
                 findings: List[Finding]) -> None:
    stages = _stages(body)
    if len(stages) < 2:
        return
    last_stmt = stages[-1][0]
    for stmt, guarded, call in stages:
        if guarded:
            continue
        if allow_last_unguarded and stmt is last_stmt:
            continue
        name = ""
        if call is not None and isinstance(call.func, ast.Attribute):
            name = f".{call.func.attr}()"
        elif call is not None and isinstance(call.func, ast.Name):
            name = f"{call.func.id}()"
        line = call.lineno if call is not None else stmt.lineno
        findings.append(Finding(
            NAME, path, line,
            f"unguarded cleanup stage {name} in {where}: a raise here "
            f"skips the {len(stages)}-stage teardown's remaining "
            f"steps — wrap each stage in its own try/except"))


def run(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for qn, info in project.index.functions.items():
        fn = info.node
        short = ".".join(qn.split(".")[-2:])
        for node in ast.walk(fn):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node is not fn:
                continue
            if isinstance(node, ast.Try) and node.finalbody:
                _check_block(node.finalbody, info.module.src.path,
                             f"{short} finally-block",
                             allow_last_unguarded=False,
                             findings=findings)
        if fn.name in CLEANUP_FUNC_NAMES:
            _check_block(fn.body, info.module.src.path,
                         f"{short}()", allow_last_unguarded=True,
                         findings=findings)
    return findings
