"""jax_compat: JAX API-rot static analysis.

JAX moves its partitioning surface roughly once a year
(``jax.experimental.maps``/``sharded_jit`` → ``pjit`` →
``jax.sharding`` + ``jax.experimental.shard_map`` → top-level
``jax.shard_map``), and each move has historically rotted exactly the
modules that called the APIs directly: the 52-test shard_map family
(parallel/, spmd/zero, adapters) was red from PR 3 to PR 20 because
call sites were written against one release's spelling. The repair is
structural — ONE sanctioned shim module
(``horovod_tpu/compat/jaxshim.py``) pays the version tax — and this
analyzer keeps it that way. Three checks:

1. **Removed/renamed API table.** A version-ranged table of JAX
   symbols that do not exist across the whole supported span
   (:data:`SUPPORTED_FLOOR` .. any future release). Any import or
   attribute use of a tabled symbol outside the shim is a finding
   naming the range and the replacement. Both directions of rot are
   covered: symbols *removed* before the span's future edge
   (``jax.experimental.maps``) and symbols *introduced* above the
   floor (``jax.shard_map``).

2. **Shim-only construction.** Mesh/sharding construction —
   ``Mesh(...)``, ``NamedSharding(...)``, ``mesh_utils.*``,
   ``jax.make_mesh``, any ``shard_map``, ``with_sharding_constraint``,
   ``lax.psum_scatter`` — must route through the jaxshim wrappers.
   These are precisely the call families each JAX migration has
   re-spelled; one call site per family keeps the next migration a
   one-module diff.

3. **PartitionSpec axis-name coherence.** Every *literal* axis name in
   a ``PartitionSpec`` must be an axis of a mesh whose axis names are
   statically known in the same lexical scope (function body, falling
   back to module level). A misspelled or stale axis name does not
   error at trace time — it silently replicates (or silently
   reshards), the exact rot class the shard_map tests died of.
   Conservative: scopes containing a mesh whose axes cannot be
   resolved statically are skipped, as are non-literal spec entries.

Blind spots (accepted): meshes received as function parameters
(callers are checked at *their* construction site), axis names routed
through variables, and specs built by helper functions — all resolve
to "statically unknown", which is skipped, never guessed.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tools.hvdlint.core import (
    Finding, ModuleIndex, Project, _expand, dotted_name, iter_executed,
)

NAME = "jax_compat"

# Single sanctioned module: the only place the tabled/construction
# APIs may appear. Matched on the module's short name so the check
# holds for fixtures and scratch trees too.
SHIM_SHORTNAME = "jaxshim"

# The oldest JAX this tree supports; mirrors
# horovod_tpu.compat.jaxshim.SUPPORTED_JAX_FLOOR (kept as a literal —
# the analyzer must not import the package under analysis) and the
# pyproject/README pin. test_lint asserts the two stay equal.
SUPPORTED_FLOOR = (0, 4, 37)

# ---------------------------------------------------------------------------
# check 1: the version-ranged API table
#
# prefix -> (introduced, removed, replacement). ``None`` introduced =
# pre-history; ``None`` removed = still shipping. A symbol is rot
# whenever its range fails to cover the whole supported span
# [SUPPORTED_FLOOR, +inf): removed is not None, or introduced above
# the floor. Longest prefix wins.

API_TABLE: Dict[str, Tuple[Optional[tuple], Optional[tuple], str]] = {
    "jax.experimental.maps": (
        None, (0, 4, 14),
        "jax.sharding Mesh/NamedSharding via "
        "compat.jaxshim.make_mesh/named_sharding"),
    "jax.experimental.sharded_jit": (
        None, (0, 2, 21),
        "jax.jit with shardings (compat.jaxshim.named_sharding)"),
    "jax.interpreters.sharded_jit": (
        None, (0, 2, 21),
        "jax.jit with shardings (compat.jaxshim.named_sharding)"),
    "jax.experimental.global_device_array": (
        None, (0, 4, 0), "jax.Array"),
    "jax.experimental.PartitionSpec": (
        None, (0, 4, 13), "jax.sharding.PartitionSpec"),
    "jax.experimental.pjit.PartitionSpec": (
        None, (0, 4, 13), "jax.sharding.PartitionSpec"),
    "jax.experimental.pjit.with_sharding_constraint": (
        None, (0, 4, 7), "compat.jaxshim.with_sharding_constraint"),
    "jax.experimental.pjit.pjit": (
        None, (0, 6, 0), "jax.jit (in_shardings/out_shardings)"),
    "jax.experimental.shard_map": (
        (0, 4, 3), (0, 8, 0), "compat.jaxshim.shard_map"),
    "jax.shard_map": (
        (0, 5, 0), None, "compat.jaxshim.shard_map"),
    "jax.lax.axis_size": (
        (0, 5, 0), None, "compat.jaxshim.axis_size"),
    # pre-0.4.26 tree aliases, removed in 0.6 (jax.tree_util / the
    # jax.tree namespace replaced them)
    "jax.tree_map": (None, (0, 6, 0), "jax.tree_util.tree_map"),
    "jax.tree_multimap": (None, (0, 3, 16), "jax.tree_util.tree_map"),
    "jax.tree_flatten": (None, (0, 6, 0), "jax.tree_util.tree_flatten"),
    "jax.tree_unflatten": (
        None, (0, 6, 0), "jax.tree_util.tree_unflatten"),
    "jax.tree_leaves": (None, (0, 6, 0), "jax.tree_util.tree_leaves"),
    "jax.tree_structure": (
        None, (0, 6, 0), "jax.tree_util.tree_structure"),
    "jax.tree_transpose": (
        None, (0, 6, 0), "jax.tree_util.tree_transpose"),
}

# check 2: construction families that must route through the shim.
# Matched on the resolved dotted tail (module-qualified or bare
# from-import), calls only.
_CONSTRUCTION = {
    "jax.sharding.Mesh": "make_mesh/make_raw_mesh",
    "jax.sharding.NamedSharding": "named_sharding",
    "jax.experimental.mesh_utils.create_device_mesh": "make_mesh",
    "jax.experimental.mesh_utils.create_hybrid_device_mesh":
        "make_hybrid_mesh",
    "jax.make_mesh": "make_mesh",
    "jax.lax.with_sharding_constraint": "with_sharding_constraint",
    "jax.lax.psum_scatter": "psum_scatter",
}

# spec/mesh factory spellings recognized by check 3 (resolved names)
_SPEC_FACTORIES = {"jax.sharding.PartitionSpec",
                   "horovod_tpu.compat.jaxshim.partition_spec",
                   "compat.jaxshim.partition_spec",
                   "jaxshim.partition_spec"}
_MESH_DICT_FACTORIES = {"horovod_tpu.compat.jaxshim.make_mesh",
                        "compat.jaxshim.make_mesh",
                        "jaxshim.make_mesh",
                        "horovod_tpu.spmd.create_mesh",
                        "spmd.create_mesh"}
_MESH_HYBRID_FACTORIES = {"horovod_tpu.compat.jaxshim.make_hybrid_mesh",
                          "compat.jaxshim.make_hybrid_mesh",
                          "jaxshim.make_hybrid_mesh",
                          "horovod_tpu.spmd.create_hybrid_mesh",
                          "spmd.create_hybrid_mesh"}
_MESH_NAMES_FACTORIES = {"jax.sharding.Mesh",
                         "horovod_tpu.compat.jaxshim.make_raw_mesh",
                         "compat.jaxshim.make_raw_mesh",
                         "jaxshim.make_raw_mesh",
                         "jax.make_mesh"}


def _fmt(v: Optional[tuple]) -> str:
    return ".".join(str(x) for x in v) if v else "?"


def _is_shim(modname: str) -> bool:
    return modname.rsplit(".", 1)[-1] == SHIM_SHORTNAME


class _FileImports:
    """ModuleIndex-shaped import maps that also see *function-scoped*
    imports — the tree's jax imports are overwhelmingly deferred into
    function bodies (import-cost hygiene), which the core indexer
    deliberately ignores. Whole-file merge: a local name imported two
    ways in different functions is vanishingly rare and resolves to
    the last spelling, which is wrong-but-loud, never silent."""

    def __init__(self, mod: ModuleIndex):
        self.imports: Dict[str, str] = dict(mod.imports)
        self.from_imports: Dict[str, Tuple[str, str]] = \
            dict(mod.from_imports)
        for node in ast.walk(mod.src.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.imports[a.asname or
                                 a.name.split(".")[0]] = a.name
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    local = a.asname or a.name
                    self.from_imports[local] = (node.module, a.name)
                    self.imports.setdefault(
                        local, f"{node.module}.{a.name}")


def _resolved(raw: Optional[str], mod) -> Optional[str]:
    """Expand a dotted use through the file's imports."""
    return _expand(raw, mod)


def _table_hit(full: str) -> Optional[Tuple[str, tuple]]:
    """Longest API_TABLE prefix that ``full`` falls under."""
    best = None
    for prefix, entry in API_TABLE.items():
        if full == prefix or full.startswith(prefix + "."):
            if best is None or len(prefix) > len(best[0]):
                best = (prefix, entry)
    return best


def _out_of_span(entry) -> bool:
    introduced, removed, _ = entry
    return removed is not None or \
        (introduced is not None and introduced > SUPPORTED_FLOOR)


def _jax_tails(full: str) -> List[str]:
    """Candidate keys for the construction table: the full resolved
    name plus shortened tails ('a.b.c.d' -> 'c.d')."""
    out = [full]
    parts = full.split(".")
    if len(parts) > 2:
        out.append(".".join(parts[-2:]))
    return out


# ---------------------------------------------------------------------------
# checks 1 + 2: tabled symbols and unsanctioned construction
# ---------------------------------------------------------------------------

def _scan_rot(src, mod, findings: List[Finding]) -> None:
    reported: Set[Tuple[int, str]] = set()

    def report_table(full: str, line: int) -> None:
        hit = _table_hit(full)
        if hit is None or not _out_of_span(hit[1]):
            return
        prefix, (introduced, removed, repl) = hit
        key = (line, prefix)
        if key in reported:
            return
        reported.add(key)
        if removed is not None and introduced is not None:
            span = (f"exists only in jax "
                    f"[{_fmt(introduced)}, {_fmt(removed)})")
        elif removed is not None:
            span = f"removed in jax {_fmt(removed)}"
        else:
            span = (f"introduced in jax {_fmt(introduced)}, above the "
                    f"supported floor {_fmt(SUPPORTED_FLOOR)}")
        findings.append(Finding(
            NAME, src.path, line,
            f"{prefix} does not span the supported jax range "
            f"(>= {_fmt(SUPPORTED_FLOOR)}): {span} — use {repl}; only "
            f"horovod_tpu/compat/jaxshim.py may touch version-ranged "
            f"jax API directly"))

    for node in ast.walk(src.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                report_table(alias.name, node.lineno)
        elif isinstance(node, ast.ImportFrom) and node.module and \
                node.module.split(".", 1)[0] == "jax":
            for alias in node.names:
                # importing a constructor is fine; *calling* it is
                # flagged below via name expansion
                report_table(f"{node.module}.{alias.name}", node.lineno)
        elif isinstance(node, ast.Attribute):
            raw = dotted_name(node)
            if raw is None:
                continue
            full = _resolved(raw, mod)
            if full is not None:
                report_table(full, node.lineno)
        elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            if node.id in mod.from_imports:
                full = _resolved(node.id, mod)
                if full is not None and full.split(".")[0] == "jax":
                    report_table(full, node.lineno)
        if isinstance(node, ast.Call):
            raw = dotted_name(node.func)
            full = _resolved(raw, mod) if raw else None
            if full is None:
                continue
            for key in _jax_tails(full):
                wrapper = _CONSTRUCTION.get(key)
                if wrapper is not None:
                    findings.append(Finding(
                        NAME, src.path, node.lineno,
                        f"direct {key} construction — route it through "
                        f"horovod_tpu.compat.jaxshim.{wrapper} so the "
                        f"next jax migration is a one-module diff"))
                    break


# ---------------------------------------------------------------------------
# check 3: PartitionSpec axis-name coherence
# ---------------------------------------------------------------------------

def _literal_strs(node: ast.AST) -> Optional[List[str]]:
    """['a', 'b'] for a literal tuple/list of strings (or one string);
    None when any element is not a string constant."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and \
                    isinstance(elt.value, str):
                out.append(elt.value)
            else:
                return None
        return out
    return None


def _mesh_axes_of_call(call: ast.Call, mod
                       ) -> Optional[Set[str]]:
    """Axis-name set for a statically-resolvable mesh construction;
    None when this call is not a mesh factory. The sentinel set
    {'?'} means "mesh factory, axes unknown" — poisons the scope."""
    raw = dotted_name(call.func)
    full = _resolved(raw, mod) if raw else None
    if full is None:
        return None
    keys = set(_jax_tails(full))

    def dict_keys(node) -> Optional[Set[str]]:
        if isinstance(node, ast.Dict):
            out = set()
            for k in node.keys:
                if isinstance(k, ast.Constant) and \
                        isinstance(k.value, str):
                    out.add(k.value)
                else:
                    return None
            return out
        if isinstance(node, ast.Constant) and node.value is None:
            return {"data"}
        return None

    if keys & _MESH_DICT_FACTORIES:
        if not call.args and not call.keywords:
            return {"data"}
        arg = call.args[0] if call.args else None
        for kw in call.keywords:
            if kw.arg == "axes":
                arg = kw.value
        axes = dict_keys(arg) if arg is not None else {"data"}
        return axes if axes is not None else {"?"}
    if keys & _MESH_HYBRID_FACTORIES:
        args = list(call.args) + [kw.value for kw in call.keywords
                                  if kw.arg in ("ici_axes", "dcn_axes")]
        out: Set[str] = set()
        for a in args[:2]:
            d = dict_keys(a)
            if d is None:
                return {"?"}
            out |= d
        return out or {"?"}
    if keys & _MESH_NAMES_FACTORIES:
        names_node = None
        if len(call.args) >= 2:
            names_node = call.args[1]
        for kw in call.keywords:
            if kw.arg in ("axis_names", "names"):
                names_node = kw.value
        if names_node is None:
            return {"?"}
        names = _literal_strs(names_node)
        return set(names) if names is not None else {"?"}
    return None


def _spec_axis_literals(call: ast.Call, mod
                        ) -> Optional[List[Tuple[str, int]]]:
    """(axis, line) pairs for the literal string axes of a
    PartitionSpec construction; None when the call is not one."""
    raw = dotted_name(call.func)
    full = _resolved(raw, mod) if raw else None
    if full is None:
        return None
    if not set(_jax_tails(full)) & _SPEC_FACTORIES:
        return None
    out: List[Tuple[str, int]] = []
    for arg in call.args:
        names = _literal_strs(arg)
        if names is not None:
            out.extend((n, arg.lineno) for n in names)
    return out


def _check_scope(body_iter, src, mod,
                 module_axes: Optional[Set[str]],
                 findings: List[Finding]) -> None:
    """One lexical scope: gather statically-known mesh axes, then
    check every literal PartitionSpec axis against their union."""
    axes: Set[str] = set()
    unknown = False
    specs: List[Tuple[str, int]] = []
    for node in body_iter:
        if not isinstance(node, ast.Call):
            continue
        mesh_axes = _mesh_axes_of_call(node, mod)
        if mesh_axes is not None:
            if "?" in mesh_axes:
                unknown = True
            else:
                axes |= mesh_axes
        lits = _spec_axis_literals(node, mod)
        if lits:
            specs.extend(lits)
    if module_axes:
        axes |= module_axes
    if unknown or not axes:
        return  # no provable mesh in scope — never guess
    for axis, line in specs:
        if axis not in axes:
            findings.append(Finding(
                NAME, src.path, line,
                f"PartitionSpec axis {axis!r} is not an axis of any "
                f"mesh in lexical scope (known axes: "
                f"{sorted(axes)}) — a stale/misspelled axis name "
                f"silently replicates instead of sharding"))


def _module_level_axes(src, mod) -> Tuple[Set[str], bool]:
    axes: Set[str] = set()
    unknown = False
    for stmt in src.tree.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        for sub in ast.walk(stmt):
            if not isinstance(sub, ast.Call):
                continue
            mesh_axes = _mesh_axes_of_call(sub, mod)
            if mesh_axes is not None:
                if "?" in mesh_axes:
                    unknown = True
                else:
                    axes |= mesh_axes
    return axes, unknown


def _scan_axis_coherence(src, mod, index,
                         findings: List[Finding]) -> None:
    module_axes, module_unknown = _module_level_axes(src, mod)
    # module level as its own scope
    top = [n for stmt in src.tree.body
           if not isinstance(stmt, (ast.FunctionDef,
                                    ast.AsyncFunctionDef, ast.ClassDef))
           for n in ast.walk(stmt)]
    if not module_unknown:
        _check_scope(top, src, mod, None, findings)
    # each function: own body (non-nested), module axes as fallback
    for info in index.functions.values():
        if info.module.src is not src:
            continue
        fallback = None if module_unknown else module_axes
        _check_scope(iter_executed(info.node), src, mod, fallback,
                     findings)


# ---------------------------------------------------------------------------

def run(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for src in project.files:
        if _is_shim(src.modname):
            continue
        imports = _FileImports(project.index.modules[src.modname])
        _scan_rot(src, imports, findings)
        _scan_axis_coherence(src, imports, project.index, findings)
    return findings
