"""native-codec: the C/Python ABI mirror of the native core.

Scope: modules named ``native`` (the ctypes loader) plus the C header
they bind (``../native/hvdtpu.h`` relative to the scanned package —
the layout horovod_tpu/native.py hardcodes). The zero-copy data plane
moved framing and reduction into C; the Python side describes every
entry point to ctypes by hand, and NOTHING checks that description
against the header — a drifted argtype is silent memory corruption,
not an exception. Four bug classes:

1. **Unmirrored entry points.** Every ``hvd_*`` function declared in
   the header must have BOTH ``lib.hvd_x.argtypes = [...]`` and
   ``lib.hvd_x.restype = ...`` assignments in the loader, and every
   configured name must exist in the header (a binding for a deleted
   symbol would raise only at call time, on the hot path).

2. **Arity drift.** ``len(argtypes)`` must equal the C declaration's
   parameter count — the exact mismatch that shifts every later
   argument one slot over and scribbles through a stale pointer.

3. **Frame-tag distinctness.** The native steady cycle receives raw
   ``TAG_*`` bytes from Python and byte-compares frames against them;
   modules named ``controller`` must keep all ``TAG_*`` constants
   pairwise distinct and within u8 (the FRAME_* discriminator rule of
   the wire-protocol analyzer, extended to the transport tags the C
   codec sees).

4. **Allocation discipline.** The entry points that malloc buffers
   back to Python (gather/recv/steady deviation paths) must be
   balanced by ``hvd_free`` in the same module — a wrapper module
   that consumes frames but never frees is a per-cycle leak.

Residual blind spots (accepted): the header parse is regex-based over
``extern "C"`` declarations — exotic C syntax (macros expanding to
declarations) would be missed; argtype WIDTHS are not checked against
C types, only arity.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Tuple

from tools.hvdlint.core import Finding, Project, SourceFile, dotted_name

NAME = "native-codec"

# hvd_* entry points whose out-params hand malloc'd buffers to Python.
# The reactor additions: the batched gather spills deviation frames
# (dev_buf) and the chunked relay spills oversize/deviation payloads
# (*spill) — both malloc'd in C, freed by the Python caller.
ALLOCATING = {"hvd_gather_frames", "hvd_recv_into",
              "hvd_steady_worker", "hvd_steady_coord",
              "hvd_gather_frames_batched", "hvd_relay_frame"}

_DECL_RE = re.compile(
    r"^\s*(?:int|void|int64_t|uint8_t)\s+(hvd_\w+)\s*\(([^;{]*)\)\s*;",
    re.MULTILINE | re.DOTALL)


def _is_native_module(src: SourceFile) -> bool:
    return src.shortname == "native"


def _header_for(src: SourceFile) -> Optional[str]:
    """The C header the loader binds: <pkg>/../native/hvdtpu.h —
    the path horovod_tpu/native.py derives at import time."""
    pkg_dir = os.path.dirname(os.path.abspath(src.path))
    path = os.path.join(os.path.dirname(pkg_dir), "native", "hvdtpu.h")
    return path if os.path.isfile(path) else None


def _split_params(arglist: str) -> List[str]:
    """Split a C parameter list on top-level commas (function-pointer
    parameters carry parentheses of their own)."""
    args, depth, cur = [], 0, []
    for ch in arglist:
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        if ch == "," and depth == 0:
            args.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    tail = "".join(cur).strip()
    if tail:
        args.append(tail)
    return [a.strip() for a in args if a.strip()]


def parse_header(text: str) -> Dict[str, int]:
    """{hvd_name: parameter count} from an extern-"C" header."""
    decls: Dict[str, int] = {}
    for m in _DECL_RE.finditer(text):
        name, arglist = m.group(1), m.group(2)
        params = _split_params(arglist)
        if len(params) == 1 and params[0] in ("void", ""):
            params = []
        decls[name] = len(params)
    return decls


def _configured(src: SourceFile) -> Tuple[Dict[str, Tuple[int, int]],
                                          Dict[str, int]]:
    """(argtypes {name: (count, line)}, restypes {name: line}) from
    ``lib.hvd_x.argtypes = [...]`` / ``.restype = ...`` assignments."""
    argtypes: Dict[str, Tuple[int, int]] = {}
    restypes: Dict[str, int] = {}
    for node in ast.walk(src.tree):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
            continue
        tgt = node.targets[0]
        if not (isinstance(tgt, ast.Attribute)
                and isinstance(tgt.value, ast.Attribute)
                and tgt.value.attr.startswith("hvd_")):
            continue
        fn = tgt.value.attr
        if tgt.attr == "argtypes":
            if isinstance(node.value, (ast.List, ast.Tuple)):
                argtypes[fn] = (len(node.value.elts), node.lineno)
            else:
                argtypes[fn] = (-1, node.lineno)  # unresolvable
        elif tgt.attr == "restype":
            restypes[fn] = node.lineno
    return argtypes, restypes


def _check_loader(src: SourceFile) -> List[Finding]:
    findings: List[Finding] = []
    header = _header_for(src)
    if header is None:
        return findings  # no native tree next to this package
    with open(header, encoding="utf-8") as fh:
        decls = parse_header(fh.read())
    argtypes, restypes = _configured(src)
    for fn, nparams in sorted(decls.items()):
        if fn not in argtypes:
            findings.append(Finding(
                NAME, src.path, 1,
                f"{fn} is declared in {os.path.basename(header)} but "
                f"has no ctypes argtypes mirror — an unchecked call "
                f"corrupts memory instead of raising"))
            continue
        count, line = argtypes[fn]
        if count >= 0 and count != nparams:
            findings.append(Finding(
                NAME, src.path, line,
                f"{fn} argtypes lists {count} parameters but the C "
                f"declaration has {nparams} — every later argument "
                f"shifts one slot (silent memory corruption)"))
        if fn not in restypes:
            findings.append(Finding(
                NAME, src.path, argtypes[fn][1],
                f"{fn} has argtypes but no restype — ctypes defaults "
                f"to c_int, truncating 64-bit returns"))
    for fn, (_, line) in sorted(argtypes.items()):
        if fn not in decls:
            findings.append(Finding(
                NAME, src.path, line,
                f"{fn} is configured for ctypes but not declared in "
                f"{os.path.basename(header)} — the binding raises "
                f"only at call time, on the hot path"))
    return findings


def _check_tags(src: SourceFile) -> List[Finding]:
    findings: List[Finding] = []
    seen: Dict[int, Tuple[str, int]] = {}
    for node in src.tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            continue
        name = node.targets[0].id
        if not name.startswith("TAG_"):
            continue
        if not (isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, int)):
            continue
        v = node.value.value
        if not 0 <= v <= 0xFF:
            findings.append(Finding(
                NAME, src.path, node.lineno,
                f"transport tag {name} = {v} does not fit the u8 tag "
                f"byte of the frame header"))
        elif v in seen:
            findings.append(Finding(
                NAME, src.path, node.lineno,
                f"transport tags {seen[v][0]} and {name} share byte "
                f"value {v:#04x} — the native codec byte-compares "
                f"tags and cannot tell these frames apart"))
        else:
            seen[v] = (name, node.lineno)
    return findings


def _check_free_discipline(src: SourceFile) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.FunctionDef):
            continue
        calls = set()
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                d = dotted_name(sub.func) or ""
                calls.add(d.rsplit(".", 1)[-1])
            elif isinstance(sub, ast.Attribute):
                calls.add(sub.attr)
        alloc = sorted(calls & ALLOCATING)
        if alloc and "hvd_free" not in calls:
            findings.append(Finding(
                NAME, src.path, node.lineno,
                f"{node.name} calls {', '.join(alloc)} (which may "
                f"malloc buffers back to Python) but never references "
                f"hvd_free — a per-cycle native memory leak"))
    return findings


def run(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for src in project.files:
        if _is_native_module(src):
            findings.extend(_check_loader(src))
        if src.shortname == "controller" \
                or src.shortname.startswith("controller_"):
            findings.extend(_check_tags(src))
        # free discipline applies anywhere the allocating entry points
        # are driven from (loader wrappers, steady-cycle drivers).
        findings.extend(_check_free_discipline(src))
    return findings
