"""hvdlint core: project model shared by every analyzer.

hvdlint encodes THIS codebase's own invariants — the bug classes the
last three PRs each had to fix by hand in review (the PACKED envelope
collision, the truncated-frame IndexError, the skipped teardown stage)
— as machine checks that run in tier-1. It is stdlib-only (ast +
tokenize) on purpose: the lint tier must run anywhere the tests run.

The model here is deliberately *unsound but precise*: calls that
cannot be resolved with high confidence (arbitrary callbacks, duck-
typed receivers) are ignored rather than guessed at, because a static
gate that cries wolf gets deleted. Each analyzer documents the
residual blind spots it accepts.

Suppressions: a finding may be silenced with a pragma on the flagged
line or the line directly above it::

    something_flagged()  # hvdlint: disable=lock-order -- why it is safe

The justification after ``--`` is mandatory; a bare pragma is itself
reported (analyzer id ``pragma``).
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

# ---------------------------------------------------------------------------
# findings + suppression pragmas

_PRAGMA_RE = re.compile(
    r"#\s*hvdlint:\s*(?:disable=)?([\w,-]+)"
    r"(?:\s*--\s*(\S.*))?")
_MARKER_RE = re.compile(r"#\s*hvdlint:\s*world-replicated\b")
# Field-scoped audit pragmas (thread-ownership analyzer): attach to a
# field's declaration or any write site; the justification after
# ``--`` is mandatory, exactly like disable= pragmas.
_OWNED_BY_RE = re.compile(
    r"#\s*hvdlint:\s*owned-by=([\w.-]+)"
    r"(?:\s*--\s*(\S.*))?")
_SNAPSHOT_RE = re.compile(
    r"#\s*hvdlint:\s*snapshot-swapped\b"
    r"(?:\s*--\s*(\S.*))?")


@dataclass(frozen=True)
class Finding:
    analyzer: str
    path: str
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.analyzer}] {self.message}"

    def to_dict(self) -> dict:
        return {"analyzer": self.analyzer, "path": self.path,
                "line": self.line, "message": self.message}


class SourceFile:
    """One parsed module: AST + pragma/marker line indexes."""

    def __init__(self, path: str, modname: str, text: str):
        self.path = path
        self.modname = modname          # dotted, e.g. horovod_tpu.common.wire
        self.shortname = modname.rsplit(".", 1)[-1]
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=path)
        # line -> set of analyzer ids silenced on that line and the next
        self.suppressions: Dict[int, set] = {}
        self.bad_pragmas: List[int] = []    # pragma without justification
        self.replicated_lines: set = set()  # '# hvdlint: world-replicated'
        # line -> audited owner role ('# hvdlint: owned-by=<role> -- why')
        self.owned_by_lines: Dict[int, str] = {}
        # line present in '# hvdlint: snapshot-swapped -- why'
        self.snapshot_lines: set = set()
        self._scan_comments()

    def _scan_comments(self) -> None:
        try:
            toks = tokenize.generate_tokens(io.StringIO(self.text).readline)
            for tok in toks:
                if tok.type != tokenize.COMMENT:
                    continue
                line = tok.start[0]
                if _MARKER_RE.search(tok.string):
                    self.replicated_lines.add(line)
                    continue
                m = _OWNED_BY_RE.search(tok.string)
                if m:
                    if not m.group(2):
                        self.bad_pragmas.append(line)
                    self.owned_by_lines[line] = m.group(1)
                    continue
                m = _SNAPSHOT_RE.search(tok.string)
                if m:
                    if not m.group(1):
                        self.bad_pragmas.append(line)
                    self.snapshot_lines.add(line)
                    continue
                m = _PRAGMA_RE.search(tok.string)
                if not m or "disable" not in tok.string:
                    continue
                names = {n.strip() for n in m.group(1).split(",") if n.strip()}
                if not m.group(2):
                    self.bad_pragmas.append(line)
                self.suppressions.setdefault(line, set()).update(names)
        except tokenize.TokenError:
            pass

    def suppressed(self, analyzer: str, line: int) -> bool:
        for pragma_line in (line, line - 1):
            names = self.suppressions.get(pragma_line)
            if names and (analyzer in names or "all" in names):
                return True
        return False


# ---------------------------------------------------------------------------
# attribute / local type tags
#
# Tags: ("lock", id, reentrant) | ("cond", id) | ("thread",) | ("event",)
#       ("queue",) | ("socket",) | ("class", qualname)

_LOCK_FACTORIES = {"threading.Lock": False, "threading.RLock": True,
                   "lockdep.lock": False, "lockdep.rlock": True}
_COND_FACTORIES = ("threading.Condition", "lockdep.condition")
_SIMPLE_FACTORIES = {
    "threading.Thread": ("thread",),
    "threading.Event": ("event",),
    "queue.Queue": ("queue",), "queue.LifoQueue": ("queue",),
    "queue.PriorityQueue": ("queue",), "queue.SimpleQueue": ("queue",),
    "socket.socket": ("socket",), "network.listen": ("socket",),
    "threading.local": ("tlocal",),
}


def dotted_name(node: ast.AST) -> Optional[str]:
    """'a.b.c' for Name/Attribute chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class ClassIndex:
    def __init__(self, module: "ModuleIndex", node: ast.ClassDef):
        self.module = module
        self.node = node
        self.name = node.name
        self.qualname = f"{module.modname}.{node.name}"
        self.bases = [dotted_name(b) for b in node.bases]
        self.methods: Dict[str, ast.FunctionDef] = {}
        self.attr_types: Dict[str, tuple] = {}
        # attr -> line of the assignment that declared it world-replicated
        self.replicated_attrs: Dict[str, int] = {}


class ModuleIndex:
    def __init__(self, src: SourceFile):
        self.src = src
        self.modname = src.modname
        self.classes: Dict[str, ClassIndex] = {}
        self.functions: Dict[str, ast.FunctionDef] = {}
        # import alias -> dotted module name ("hlog" -> "...common.logging")
        self.imports: Dict[str, str] = {}
        # from-import: local name -> (module, symbol)
        self.from_imports: Dict[str, Tuple[str, str]] = {}
        self.attr_types: Dict[str, tuple] = {}  # module-level vars
        self.constants: Dict[str, ast.AST] = {}  # module-level assignments


class FuncInfo:
    """Per-function facts gathered by the indexer."""

    def __init__(self, qualname: str, module: ModuleIndex,
                 cls: Optional[ClassIndex], node: ast.FunctionDef):
        self.qualname = qualname
        self.module = module
        self.cls = cls
        self.node = node
        self.decorators = {dotted_name(d) or "" for d in node.decorator_list}
        self.local_types: Dict[str, tuple] = {}


class ProjectIndex:
    def __init__(self):
        self.modules: Dict[str, ModuleIndex] = {}
        self.functions: Dict[str, FuncInfo] = {}   # qualname -> info
        # short module name -> ModuleIndex (for import resolution against
        # scanned files regardless of package prefix)
        self.by_short: Dict[str, ModuleIndex] = {}

    def class_by_name(self, name: str) -> Optional[ClassIndex]:
        for mod in self.modules.values():
            ci = mod.classes.get(name)
            if ci is not None:
                return ci
        return None


class Project:
    """The file set under analysis plus its cross-module index."""

    def __init__(self, roots: List[str]):
        self.roots = [os.path.abspath(r) for r in roots]
        self.files: List[SourceFile] = []
        for root in self.roots:
            if os.path.isfile(root):
                self._add(root, os.path.splitext(os.path.basename(root))[0])
                continue
            base = os.path.basename(root.rstrip(os.sep))
            for dirpath, dirnames, filenames in os.walk(root):
                dirnames[:] = sorted(d for d in dirnames
                                     if d != "__pycache__")
                for fn in sorted(filenames):
                    if not fn.endswith(".py"):
                        continue
                    path = os.path.join(dirpath, fn)
                    rel = os.path.relpath(path, root)
                    mod = rel[:-3].replace(os.sep, ".")
                    if mod.endswith(".__init__"):
                        mod = mod[:-len(".__init__")]
                    modname = base if mod == "__init__" else f"{base}.{mod}"
                    self._add(path, modname)
        self.index = _build_index(self)
        self.resolver = Resolver(self.index)

    def _add(self, path: str, modname: str) -> None:
        with open(path, encoding="utf-8") as f:
            text = f.read()
        self.files.append(SourceFile(path, modname, text))

    def doc_root(self) -> Optional[str]:
        """Directory holding docs/ + README.md: the parent of the first
        scanned root (repo layout), if it actually has either."""
        parent = os.path.dirname(self.roots[0].rstrip(os.sep))
        if os.path.isdir(os.path.join(parent, "docs")) or \
                os.path.isfile(os.path.join(parent, "README.md")):
            return parent
        return None


# ---------------------------------------------------------------------------
# indexing

def _expand(dotted: Optional[str], mod: ModuleIndex) -> Optional[str]:
    """Resolve the leading component of a dotted name through the
    module's imports ("hlog.warning" -> "...common.logging.warning")."""
    if not dotted:
        return None
    head, _, rest = dotted.partition(".")
    if head in mod.from_imports:
        fmod, sym = mod.from_imports[head]
        head = f"{fmod}.{sym}"
    elif head in mod.imports:
        head = mod.imports[head]
    return f"{head}.{rest}" if rest else head


def _type_of_value(expr: ast.AST, mod: ModuleIndex, index: ProjectIndex,
                   owner: Optional[str] = None,
                   attrs: Optional[Dict[str, tuple]] = None
                   ) -> Optional[tuple]:
    """Type tag for the right-hand side of an assignment."""
    if not isinstance(expr, ast.Call):
        return None
    raw = dotted_name(expr.func)
    if raw is None:
        return None
    tail = raw.rsplit(".", 1)[-1]
    full = _expand(raw, mod) or raw
    # normalize "horovod_tpu.common.lockdep.lock" -> "lockdep.lock" etc.
    short2 = ".".join(full.split(".")[-2:])
    for key in (raw, short2):
        if key in _LOCK_FACTORIES:
            name = None
            if key.startswith("lockdep.") and expr.args and \
                    isinstance(expr.args[0], ast.Constant) and \
                    isinstance(expr.args[0].value, str):
                name = expr.args[0].value
            return ("lock", name, _LOCK_FACTORIES[key])
        if key in _COND_FACTORIES:
            # Condition(existing_lock) shares that lock; no-arg owns one.
            for arg in expr.args:
                d = dotted_name(arg)
                if d and d.startswith("self.") and attrs is not None:
                    return ("cond_alias", d.split(".", 1)[1])
                if isinstance(arg, ast.Constant) and \
                        isinstance(arg.value, str):
                    return ("cond", arg.value)
            return ("cond", None)
        if key in _SIMPLE_FACTORIES:
            return _SIMPLE_FACTORIES[key]
    # project class constructor?
    cls = _resolve_class_name(raw, mod, index)
    if cls is not None:
        return ("class", cls.qualname)
    if tail in ("Thread",):
        return ("thread",)
    return None


def _resolve_class_name(raw: str, mod: ModuleIndex,
                        index: ProjectIndex) -> Optional[ClassIndex]:
    head, _, rest = raw.partition(".")
    if not rest:
        if head in mod.classes:
            return mod.classes[head]
        if head in mod.from_imports:
            fmod, sym = mod.from_imports[head]
            target = index.modules.get(fmod) or index.by_short.get(
                fmod.rsplit(".", 1)[-1])
            if target is not None:
                return target.classes.get(sym)
        return None
    if "." in rest:
        return None
    target = None
    if head in mod.imports:
        full = mod.imports[head]
        target = index.modules.get(full) or index.by_short.get(
            full.rsplit(".", 1)[-1])
    if target is not None:
        return target.classes.get(rest)
    return None


def _type_from_annotation(ann: ast.AST, mod: ModuleIndex,
                          index: ProjectIndex) -> Optional[tuple]:
    """('class', qualname) from an annotation like Optional[ResponseCache]
    or a string annotation."""
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        try:
            ann = ast.parse(ann.value, mode="eval").body
        except SyntaxError:
            return None
    for node in ast.walk(ann):
        if isinstance(node, ast.Name):
            cls = _resolve_class_name(node.id, mod, index)
            if cls is not None:
                return ("class", cls.qualname)
    return None


def _collect_attr_types(ci: ClassIndex, index: ProjectIndex) -> None:
    mod = ci.module
    src = mod.src
    for meth in ci.methods.values():
        for node in ast.walk(meth):
            target = None
            value = None
            ann = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                target, value = node.targets[0], node.value
            elif isinstance(node, ast.AnnAssign):
                target, value, ann = node.target, node.value, node.annotation
            if not (isinstance(target, ast.Attribute)
                    and isinstance(target.value, ast.Name)
                    and target.value.id == "self"):
                continue
            attr = target.attr
            if node.lineno in src.replicated_lines or \
                    (node.end_lineno or node.lineno) in src.replicated_lines:
                ci.replicated_attrs.setdefault(attr, node.lineno)
            tag = _type_of_value(value, mod, index, attrs=ci.attr_types) \
                if value is not None else None
            if tag is None and ann is not None:
                tag = _type_from_annotation(ann, mod, index)
            if tag is not None and attr not in ci.attr_types:
                ci.attr_types[attr] = tag
    # second pass: name anonymous locks/conditions + resolve aliases
    short = mod.src.shortname
    for attr, tag in list(ci.attr_types.items()):
        if tag[0] == "lock" and tag[1] is None:
            ci.attr_types[attr] = ("lock", f"{short}.{ci.name}.{attr}",
                                   tag[2])
        elif tag[0] == "cond" and tag[1] is None:
            ci.attr_types[attr] = ("cond", f"{short}.{ci.name}.{attr}")
    for attr, tag in list(ci.attr_types.items()):
        if tag[0] == "cond_alias":
            base = ci.attr_types.get(tag[1])
            if base is not None and base[0] == "lock":
                ci.attr_types[attr] = ("cond", base[1])
            else:
                ci.attr_types[attr] = ("cond", f"{short}.{ci.name}.{attr}")


def _build_index(project: Project) -> ProjectIndex:
    index = ProjectIndex()
    for src in project.files:
        mod = ModuleIndex(src)
        index.modules[src.modname] = mod
        index.by_short[src.shortname] = mod
        for node in src.tree.body:
            if isinstance(node, ast.Import):
                for alias in node.names:
                    mod.imports[alias.asname or
                                alias.name.split(".")[0]] = alias.name
            elif isinstance(node, ast.ImportFrom) and node.module:
                for alias in node.names:
                    local = alias.asname or alias.name
                    # a from-import may name a module or a symbol; record
                    # both interpretations and let resolution decide
                    mod.from_imports[local] = (node.module, alias.name)
                    mod.imports.setdefault(
                        local, f"{node.module}.{alias.name}")
            elif isinstance(node, ast.ClassDef):
                ci = ClassIndex(mod, node)
                mod.classes[node.name] = ci
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        ci.methods[item.name] = item
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                mod.functions[node.name] = node
            elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                name = node.targets[0].id
                mod.constants[name] = node.value
                tag = _type_of_value(node.value, mod, index)
                if tag is not None:
                    if tag[0] == "lock" and tag[1] is None:
                        tag = ("lock", f"{src.shortname}.{name}", tag[2])
                    elif tag[0] == "cond" and tag[1] is None:
                        tag = ("cond", f"{src.shortname}.{name}")
                    mod.attr_types[name] = tag
    # second pass: class attribute types (needs the class table complete)
    for mod in index.modules.values():
        for ci in mod.classes.values():
            _collect_attr_types(ci, index)
    # function registry
    for mod in index.modules.values():
        for name, node in mod.functions.items():
            qn = f"{mod.modname}.{name}"
            index.functions[qn] = FuncInfo(qn, mod, None, node)
        for ci in mod.classes.values():
            for name, node in ci.methods.items():
                qn = f"{ci.qualname}.{name}"
                index.functions[qn] = FuncInfo(qn, mod, ci, node)
    for info in index.functions.values():
        info.local_types = _infer_local_types(info, index)
    return index


def _infer_local_types(info: FuncInfo, index: ProjectIndex
                       ) -> Dict[str, tuple]:
    """var -> type tag for locals assigned from typed self attributes or
    project-class constructors (one flow-insensitive pass)."""
    out: Dict[str, tuple] = {}
    mod = info.module
    for node in iter_executed(info.node):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            continue
        name = node.targets[0].id
        tag = _type_of_value(node.value, mod, index)
        if tag is None:
            d = dotted_name(node.value)
            if d and d.startswith("self.") and info.cls is not None:
                attr = d.split(".", 1)[1]
                if "." not in attr:
                    tag = info.cls.attr_types.get(attr)
        if tag is not None and name not in out:
            out[name] = tag
    return out


def iter_executed(func: ast.AST):
    """Walk a function body WITHOUT descending into nested function /
    class definitions or lambdas: their bodies run later, not here —
    statements inside them are not executed under this function's
    locks, and treating them as such manufactures false positives."""
    stack = list(ast.iter_child_nodes(func))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


# ---------------------------------------------------------------------------
# call resolution

class Resolver:
    def __init__(self, index: ProjectIndex):
        self.index = index

    def _module_of(self, dotted_mod: str) -> Optional[ModuleIndex]:
        return (self.index.modules.get(dotted_mod)
                or self.index.by_short.get(dotted_mod.rsplit(".", 1)[-1]))

    def _method(self, cls: ClassIndex, name: str) -> Optional[str]:
        seen = set()
        queue = [cls]
        while queue:
            c = queue.pop(0)
            if c.qualname in seen:
                continue
            seen.add(c.qualname)
            if name in c.methods:
                return f"{c.qualname}.{name}"
            for b in c.bases:
                if not b:
                    continue
                bc = _resolve_class_name(b, c.module, self.index)
                if bc is not None:
                    queue.append(bc)
        return None

    def resolve_call(self, call: ast.Call, info: FuncInfo) -> Optional[str]:
        """Qualname of the called project function, or None. A resolved
        class returns its __init__ when defined."""
        func = call.func
        mod = info.module
        if isinstance(func, ast.Name):
            name = func.id
            if name in mod.functions:
                return f"{mod.modname}.{name}"
            cls = _resolve_class_name(name, mod, self.index)
            if cls is not None:
                return self._method(cls, "__init__")
            if name in mod.from_imports:
                fmod, sym = mod.from_imports[name]
                target = self._module_of(fmod)
                if target is not None and sym in target.functions:
                    return f"{target.modname}.{sym}"
            return None
        if not isinstance(func, ast.Attribute):
            return None
        recv, meth = func.value, func.attr
        # self.method() / self.attr.method()
        d = dotted_name(recv)
        if d == "self" and info.cls is not None:
            return self._method(info.cls, meth)
        if d and d.startswith("self.") and info.cls is not None:
            attr = d.split(".", 1)[1]
            if "." not in attr:
                tag = info.cls.attr_types.get(attr)
                if tag and tag[0] == "class":
                    cls = self._class_by_qualname(tag[1])
                    if cls is not None:
                        return self._method(cls, meth)
            return None
        if isinstance(recv, ast.Name):
            tag = info.local_types.get(recv.id)
            if tag and tag[0] == "class":
                cls = self._class_by_qualname(tag[1])
                if cls is not None:
                    return self._method(cls, meth)
            # imported module function: hlog.warning(...)
            if recv.id in mod.imports:
                target = self._module_of(mod.imports[recv.id])
                if target is not None:
                    if meth in target.functions:
                        return f"{target.modname}.{meth}"
                    cls = target.classes.get(meth)
                    if cls is not None:
                        return self._method(cls, "__init__")
        return None

    def _class_by_qualname(self, qualname: str) -> Optional[ClassIndex]:
        modname, _, cname = qualname.rpartition(".")
        mod = self._module_of(modname)
        if mod is not None:
            return mod.classes.get(cname)
        return None

    def lock_of_expr(self, expr: ast.AST, info: FuncInfo
                     ) -> Optional[tuple]:
        """('lock'|'cond', id, reentrant) when the expression denotes a
        known lock/condition object."""
        d = dotted_name(expr)
        if d is None:
            return None
        tag = None
        if d.startswith("self.") and info.cls is not None:
            attr = d.split(".", 1)[1]
            if "." not in attr:
                tag = info.cls.attr_types.get(attr)
        elif "." not in d:
            tag = info.local_types.get(d) or \
                info.module.attr_types.get(d)
        if tag is None:
            return None
        if tag[0] == "lock":
            return ("lock", tag[1], tag[2])
        if tag[0] == "cond":
            return ("cond", tag[1], False)
        return None

    def type_of_expr(self, expr: ast.AST, info: FuncInfo
                     ) -> Optional[tuple]:
        """Full type tag (thread/event/queue/socket/class/lock/cond)."""
        d = dotted_name(expr)
        if d is None:
            return None
        if d.startswith("self.") and info.cls is not None:
            attr = d.split(".", 1)[1]
            if "." not in attr:
                return info.cls.attr_types.get(attr)
            return None
        if "." not in d:
            return info.local_types.get(d) or info.module.attr_types.get(d)
        return None


# ---------------------------------------------------------------------------
# runner

def get_analyzers() -> Dict[str, object]:
    from tools.hvdlint import (jax_compat, knobs, lock_order,
                               native_codec, native_lifetime, teardown,
                               thread_ownership, wire_protocol,
                               world_coherence)
    mods = (lock_order, thread_ownership, wire_protocol, native_codec,
            native_lifetime, world_coherence, teardown, knobs,
            jax_compat)
    return {m.NAME: m for m in mods}


def lint_paths(paths: List[str],
               analyzers: Optional[List[str]] = None) -> List[Finding]:
    project = Project(paths)
    registry = get_analyzers()
    names = analyzers or list(registry)
    unknown = [n for n in names if n not in registry]
    if unknown:
        raise ValueError(f"unknown analyzer(s) {unknown}; "
                         f"available: {sorted(registry)}")
    findings: List[Finding] = []
    for name in names:
        findings.extend(registry[name].run(project))
    by_path = {src.path: src for src in project.files}
    kept = []
    for f in findings:
        src = by_path.get(f.path)
        if src is not None and src.suppressed(f.analyzer, f.line):
            continue
        kept.append(f)
    for src in project.files:
        for line in src.bad_pragmas:
            kept.append(Finding(
                "pragma", src.path, line,
                "hvdlint suppression without a justification — append "
                "'-- <why this is safe>'"))
    kept.sort(key=lambda f: (f.path, f.line, f.analyzer))
    # de-dup identical findings from overlapping passes
    seen = set()
    out = []
    for f in kept:
        k = (f.path, f.line, f.analyzer, f.message)
        if k not in seen:
            seen.add(k)
            out.append(f)
    return out
